"""Live rank rejoin (wormhole_tpu/ft/rejoin.py): version vectors,
bounded replay, membership group, handshake, chaos knobs, torn-read
checkpoint scans, and the launcher's per-rank respawn path. The full
kill-and-rejoin drill under serving traffic is the slow e2e
(test_ft_rejoin_e2e.py)."""

import os
import sys
import tempfile
import threading
import time

import numpy as np
import pytest

from wormhole_tpu.ft.rejoin import (DeadMember, LocalGroup,
                                    RejoinHandshake, ReplayExhausted,
                                    ReplayLog, VersionVector)

from tests.test_launcher_mp import run_mp


# -- version vector ------------------------------------------------------


def test_vv_one_hot_sum_reconstructs():
    # the wire trick: each rank ships its own counter one-hot; the
    # delta allreduce's sum IS the full vector
    vvs = [VersionVector(3) for _ in range(3)]
    for r, vv in enumerate(vvs):
        vv.bump(r, r + 1)
    reduced = sum(vv.one_hot(r) for r, vv in enumerate(vvs))
    np.testing.assert_array_equal(reduced, [1, 2, 3])
    mine = VersionVector(3)
    mine.merge_row(reduced)
    assert mine.counts == [1, 2, 3]


def test_vv_merge_is_elementwise_max():
    a = VersionVector(3)
    a.merge_row([5, 0, 2])
    a.merge_row([3, 4, 1])          # stale row must not regress slot 0
    assert a.counts == [5, 4, 2]
    assert a.lag(1) == 1
    b = VersionVector(3)
    b.bump(2, 9)
    a.merge(b)
    assert a.counts == [5, 4, 9]


def test_vv_world_validation():
    with pytest.raises(ValueError):
        VersionVector(0)


# -- replay log ----------------------------------------------------------


def test_replay_record_fetch_window():
    log = ReplayLog(depth=8)
    for i in range(5):
        log.record(i, {"grad": i})
    assert log.oldest() == 0 and log.latest() == 4
    got = log.fetch(1, 3)
    assert [i for i, _ in got] == [2, 3]
    assert log.fetch(4, 4) == []     # nothing missed -> empty


def test_replay_eviction_raises_exhausted():
    log = ReplayLog(depth=3)
    for i in range(10):              # windows 0..6 evicted
        log.record(i, i)
    assert log.evicted == 7
    assert log.oldest() == 7
    with pytest.raises(ReplayExhausted):
        log.fetch(2, 9)
    # a gap the log still covers is fine
    assert [i for i, _ in log.fetch(6, 9)] == [7, 8, 9]


def test_replay_fetch_waits_for_late_record():
    # the reduce->record race: the group reduced window 2 but the
    # survivor's drain thread hasn't recorded it yet — fetch blocks
    log = ReplayLog(depth=8)
    log.record(0, 0)

    def late():
        time.sleep(0.05)
        log.record(1, 1)
        log.record(2, 2)

    t = threading.Thread(target=late)
    t.start()
    got = log.fetch(0, 2, timeout=5.0)
    t.join()
    assert [i for i, _ in got] == [1, 2]


def test_replay_fetch_timeout():
    log = ReplayLog(depth=4)
    log.record(0, 0)
    with pytest.raises(TimeoutError):
        log.fetch(0, 5, timeout=0.05)


def test_replay_depth_validation():
    with pytest.raises(ValueError):
        ReplayLog(0)


# -- local membership group ----------------------------------------------


def _reduce_on_thread(group, rank, idx, payload, out):
    def run():
        try:
            out[rank] = group.allreduce(rank, idx, payload, timeout=10)
        except BaseException as e:
            out[rank] = e
    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def test_group_allreduce_sums_all_ranks():
    g = LocalGroup(3)
    out = {}
    ts = [_reduce_on_thread(g, r, 0, {"x": np.float32(r + 1)}, out)
          for r in range(3)]
    for t in ts:
        t.join(timeout=10)
    assert all(float(out[r]["x"]) == 6.0 for r in range(3))


def test_group_mark_dead_unblocks_inflight_window():
    g = LocalGroup(3)
    out = {}
    ts = [_reduce_on_thread(g, r, 0, {"x": np.float32(1)}, out)
          for r in (0, 1)]           # rank 2 never posts
    time.sleep(0.05)
    assert g.mark_dead(2) == 1       # epoch bumped
    for t in ts:
        t.join(timeout=10)
    # window reduced over the live sub-group
    assert all(float(out[r]["x"]) == 2.0 for r in (0, 1))
    with pytest.raises(DeadMember):
        g.allreduce(2, 1, {"x": np.float32(1)})


def test_group_dead_ranks_posted_bytes_stay_in():
    # a contribution already on the wire when the rank died is included
    g = LocalGroup(3)
    out = {}
    t2 = _reduce_on_thread(g, 2, 0, {"x": np.float32(10)}, out)
    time.sleep(0.05)
    g.mark_dead(2)
    ts = [_reduce_on_thread(g, r, 0, {"x": np.float32(1)}, out)
          for r in (0, 1)]
    for t in ts + [t2]:
        t.join(timeout=10)
    assert float(out[0]["x"]) == 12.0


def test_group_attach_reserves_next_boundary():
    g = LocalGroup(2)
    out = {}
    for idx in range(3):
        ts = [_reduce_on_thread(g, r, idx, {"x": np.float32(1)}, out)
              for r in (0, 1)]
        for t in ts:
            t.join(timeout=10)
    g.detach(1)                      # graceful: no epoch bump
    assert g.epoch == 0
    join = g.attach(1)
    assert join == 3 and g.epoch == 1
    # window 3 now waits for the rejoiner's contribution
    out3 = {}
    t0 = _reduce_on_thread(g, 0, 3, {"x": np.float32(1)}, out3)
    time.sleep(0.05)
    assert 0 not in out3
    t1 = _reduce_on_thread(g, 1, 3, {"x": np.float32(5)}, out3)
    for t in (t0, t1):
        t.join(timeout=10)
    assert float(out3[0]["x"]) == 6.0


def test_handshake_attach_then_replay_in_order():
    g = LocalGroup(2)
    log = ReplayLog(depth=8)
    out = {}
    for idx in range(4):             # survivor 0 reduced windows 0..3
        ts = [_reduce_on_thread(g, r, idx, {"x": np.float32(r)}, out)
              for r in (0, 1)]
        for t in ts:
            t.join(timeout=10)
        log.record(idx, {"x": np.float32(idx)})
    g.mark_dead(1)
    applied = []
    rep = RejoinHandshake(g, log).run(1, have_idx=0,
                                      apply_fn=lambda i, p:
                                      applied.append(i))
    assert rep.join_idx == 4 and rep.replayed == 3
    assert applied == [1, 2, 3]      # ordered, (have, join) exclusive
    assert rep.epoch == g.epoch and 1 in g.live()


# -- engine records reduced windows into the replay log ------------------


def test_engine_records_successful_deltas_only():
    from wormhole_tpu.ps.engine import ExchangeEngine
    log = ReplayLog(depth=8)
    eng = ExchangeEngine(0, replay=log)
    try:
        for i in range(3):
            t = eng.submit(lambda i=i: {"grad": i})
            eng.gate()
            assert t.result == {"grad": i}
        bad = eng.submit(lambda: (_ for _ in ()).throw(RuntimeError("x")))
        with pytest.raises(RuntimeError):
            eng.gate()
        assert bad.error is not None
    finally:
        eng.stop()
    assert [i for i, _ in log.fetch(-1, 2)] == [0, 1, 2]
    assert log.latest() == 2         # the failed window was not recorded


def test_replay_depth_and_build_engine_wiring():
    from wormhole_tpu.ps.config import build_engine, replay_depth
    from wormhole_tpu.utils.config import Config
    assert replay_depth(Config(staleness_tau=2)) == 0   # knob off
    assert replay_depth(Config(staleness_tau=2,
                               rejoin_replay_windows=3)) == 5
    assert replay_depth(Config(staleness_tau=-1,
                               rejoin_replay_windows=3)) == 3
    eng = build_engine(Config(staleness_tau=1))
    try:
        assert eng.replay is None    # off by default: wire bytes and
    finally:                         # tau=0 BSP parity untouched
        eng.stop()
    eng = build_engine(Config(staleness_tau=1, rejoin_replay_windows=4))
    try:
        assert eng.replay is not None and eng.replay.depth == 5
    finally:
        eng.stop()


def test_rejoin_metrics_declared_once():
    from wormhole_tpu.obs.metrics import Registry
    from wormhole_tpu.ps.telemetry import rejoin_metrics
    met = rejoin_metrics(Registry())
    met.epoch.set(2)
    met.replayed.inc(5)
    assert met.epoch.value == 2 and met.replayed.value == 5


# -- chaos knobs ---------------------------------------------------------


def test_chaos_rejoin_handshake_delay():
    from wormhole_tpu.ft import chaos
    try:
        assert chaos.install({"rejoin_handshake_delay": 0.08}, rank=0)
        t0 = time.monotonic()
        chaos.on_rejoin_handshake()
        assert time.monotonic() - t0 >= 0.08
    finally:
        chaos.reset()
    t0 = time.monotonic()
    chaos.on_rejoin_handshake()      # disarmed -> no sleep
    assert time.monotonic() - t0 < 0.05


def test_chaos_rejoin_knobs_from_config():
    from wormhole_tpu.ft import chaos
    from wormhole_tpu.utils.config import Config
    cfg = Config(chaos_rejoin_handshake_delay_s=0.01,
                 chaos_rejoin_ckpt_transient=2)
    try:
        assert chaos.install_from_config(cfg, rank=0)
        with pytest.raises(OSError):
            chaos.rejoin_ckpt_fault("/some/dir")
        with pytest.raises(OSError):
            chaos.rejoin_ckpt_fault("/some/dir")
        chaos.rejoin_ckpt_fault("/some/dir")   # budget spent
    finally:
        chaos.reset()


def test_latest_version_retries_torn_scan(tmp_path):
    from wormhole_tpu.ft import chaos
    from wormhole_tpu.parallel.checkpoint import Checkpointer
    ck = Checkpointer(str(tmp_path))
    ck.save(3, {"w": np.ones(4, np.float32)})
    try:
        chaos.install({"rejoin_ckpt_transient": 1}, rank=0)
        assert ck.latest_version() == 3       # one fault -> one retry
        chaos.install({"rejoin_ckpt_transient": 2}, rank=0)
        with pytest.raises(OSError):          # second fault propagates
            ck.latest_version()
    finally:
        chaos.reset()
    assert ck.latest_version() == 3


def test_shard_latest_version_retries_torn_scan(tmp_path):
    from wormhole_tpu.ft import chaos
    from wormhole_tpu.parallel.checkpoint import ShardCheckpointer
    ck = ShardCheckpointer(str(tmp_path), rank=0, world=1)
    ck.save(2, {"w": np.ones(4, np.float32)}, barrier=False)
    try:
        chaos.install({"rejoin_ckpt_transient": 1}, rank=0)
        assert ck.latest_version() == 2
        chaos.install({"rejoin_ckpt_transient": 2}, rank=0)
        with pytest.raises(OSError):
            ck.latest_version()
    finally:
        chaos.reset()
    assert ck.latest_version() == 2


def test_shard_checkpointer_rank_override(tmp_path):
    # the drill's simulated ranks and the rejoiner's cross-instance
    # restore both need rank/world without jax.distributed
    from wormhole_tpu.parallel.checkpoint import ShardCheckpointer
    w = ShardCheckpointer(str(tmp_path), rank=2, world=3)
    w.save(4, {"w": np.full(4, 7, np.float32)}, barrier=False)
    r = ShardCheckpointer(str(tmp_path), rank=2, world=3)
    ver, st = r.load({"w": np.zeros(4, np.float32)})
    assert ver == 4
    np.testing.assert_array_equal(st["w"], np.full(4, 7, np.float32))


# -- supervisor + launcher respawn path ----------------------------------


def test_supervisor_rejoin_bookkeeping():
    from wormhole_tpu.ft.supervisor import Supervisor
    sup = Supervisor(3, elastic="rejoin", dead_after_s=1.0)
    assert sup.next_world() == 3
    sup.record_exit(1, 9)
    assert sup.dead == {1} and sup.epoch == 1
    assert sup.rejoinable(1) and not sup.rejoinable(0)
    assert sup.note_rejoined(1) == 2
    assert sup.dead == set() and 1 not in sup.exit_codes
    sup2 = Supervisor(3, elastic="shrink")
    sup2.record_exit(1, 9)
    assert not sup2.rejoinable(1)    # shrink keeps stop-the-world


def test_launcher_live_rejoin_no_world_relaunch():
    """rank 1 crashes on attempt 0; the launcher respawns ONLY rank 1
    into the live world (attempt dir unchanged, survivors' processes
    keep running) and the job exits clean."""
    mark = tempfile.mkdtemp(prefix="wh_rejoin_mark_")
    r = run_mp(3, f"""
        import os, sys, time
        rank = int(os.environ["PROCESS_ID"])
        attempt = int(os.environ.get("WORMHOLE_ATTEMPT", "0"))
        mark = {mark!r}
        if rank == 1 and attempt == 0:
            sys.exit(7)                    # simulated crash
        if rank == 1:
            # the respawn must carry the rejoin env contract
            assert os.environ.get("WORMHOLE_REJOIN_RANK") == "1"
            with open(os.path.join(mark, "rejoined"), "w") as f:
                f.write(str(attempt))
            sys.exit(0)
        with open(os.path.join(mark, f"pid{{rank}}"), "w") as f:
            f.write(str(os.getpid()))
        time.sleep(2.0)                    # outlive the respawn cycle
        """, launcher_args=("--ft-elastic", "rejoin", "--restarts", "1"),
        raw=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "live rejoin" in r.stderr and "survivors keep running" \
        in r.stderr, r.stderr
    assert "rejoined (membership epoch" in r.stderr, r.stderr
    # no stop-the-world: the whole-world relaunch banner never printed
    assert "supervised relaunch" not in r.stderr, r.stderr
    with open(os.path.join(mark, "rejoined")) as f:
        assert f.read() == "1"             # respawn ran as attempt 1
    assert sorted(os.listdir(mark)) == ["pid0", "pid2", "rejoined"]


def test_launcher_rejoin_budget_exhausted_fails_job():
    r = run_mp(3, """
        import os, sys, time
        rank = int(os.environ["PROCESS_ID"])
        if rank == 1:
            sys.exit(7)                    # crashes on EVERY attempt
        time.sleep(2.0)
        """, launcher_args=("--ft-elastic", "rejoin", "--restarts", "1"),
        raw=True)
    assert r.returncode == 7, r.stdout + r.stderr
    assert r.stderr.count("live rejoin") == 1, r.stderr

"""Launcher multi-process mode: real jax.distributed over localhost (the
DCN code path the reference exercises with dmlc_local.py multi-process
runs, SURVEY.md §4.3)."""

import os
import re
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def parse_num_ex(out: str):
    """Line-anchored per-rank ``num_ex`` parse (the launcher merges rank
    output line-atomically and prefixes each line with its ``[w<rank>]``
    tag; anchoring makes the parse robust even if a rank's line is
    preceded by other output)."""
    vals = [int(m) for m in
            re.findall(r"^(?:\[w\d+\] )?OK rank \d+ num_ex=(\d+)",
                       out, re.M)]
    assert vals, f"no 'OK rank N num_ex=' line in:\n{out}"
    return vals


# A jax CPU backend without multiprocess collectives rejects the
# launch almost immediately with this message; bodies that never touch
# jax.distributed (trace merges, supervised drills with plain
# children) still run fine, so the skip is decided per launch from the
# observed error — never cached across tests.
_MP_ERR = "Multiprocess computations aren't"


def _skip_if_mp_unsupported(r) -> None:
    """Skip (not fail) when the backend rejects mp collectives — the
    same guard test_ft_chaos_e2e.py applies to its supervised drills."""
    if r.returncode != 0 and _MP_ERR in r.stdout + r.stderr:
        pytest.skip("jax CPU backend lacks multiprocess collectives "
                    "in this environment")


def run_mp(n: int, body: str, timeout=240, launcher_args=(),
           raw=False):
    """Run ``body`` under the mp launcher. ``raw=True`` returns the
    CompletedProcess (for tests asserting on stderr/returncode).
    Either way an environment whose backend cannot run multiprocess
    collectives skips the caller instead of failing it."""
    script = os.path.join(REPO, ".pytest_cache", f"mp_body_{os.getpid()}.py")
    os.makedirs(os.path.dirname(script), exist_ok=True)
    with open(script, "w") as f:
        f.write(textwrap.dedent(body))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS",)}  # children get their own device count
    r = subprocess.run(
        [sys.executable, "-m", "wormhole_tpu.parallel.launcher",
         "-n", str(n), "--cluster", "mp", *launcher_args, "--",
         sys.executable, script],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)
    _skip_if_mp_unsupported(r)
    if raw:
        return r
    assert r.returncode == 0, r.stdout + r.stderr
    return r.stdout


def test_mp_collectives():
    out = run_mp(2, """
        from wormhole_tpu.parallel.mesh import MeshRuntime
        import numpy as np
        rt = MeshRuntime.create()
        assert rt.world == 2, rt.world
        from wormhole_tpu.parallel.collectives import (allreduce_tree,
                                                       broadcast_tree)
        total = allreduce_tree(np.asarray(float(rt.rank + 1)),
                               rt.mesh, "sum")
        assert float(total) == 3.0, total
        mx = allreduce_tree(np.asarray(float(rt.rank)), rt.mesh, "max")
        assert float(mx) == 1.0, mx
        root = broadcast_tree(
            np.asarray(42.0 if rt.rank == 0 else -1.0), rt.mesh)
        assert float(root) == 42.0, root
        # COMPRESSING filter analogue: zlib'd payloads reduce identically
        big = np.full(4096, float(rt.rank + 1), np.float64)
        z = allreduce_tree(big, rt.mesh, "sum", compress=True)
        assert np.allclose(np.asarray(z), 3.0), z
        print(f"OK rank {rt.rank}")
    """)
    assert out.count("OK rank") == 2


def _learnable_libsvm(tmp_path, rng, n_files=2, rows=400, dim=64):
    """Files where one planted feature decides the label."""
    paths = []
    for k in range(n_files):
        lines = []
        for _ in range(rows):
            y = rng.random() < 0.5
            feats = sorted(rng.choice(np.arange(2, dim), size=6,
                                      replace=False))
            planted = 0 if y else 1
            toks = [f"{planted}:1"] + [f"{j}:1" for j in feats]
            lines.append(f"{int(y)} " + " ".join(toks))
        p = tmp_path / f"part{k}.libsvm"
        p.write_text("\n".join(lines) + "\n")
        paths.append(str(p))
    return str(tmp_path / "part*.libsvm")


CFG_COMMON = ("data_format=libsvm num_buckets=4096 minibatch=100 "
              "max_nnz=16 key_pad=256 lr_eta=0.5 max_delay=1 "
              "disp_itv=1e12")


def test_mp_async_ftrl_converges(tmp_path):
    """2-process synchronized FTRL via the replicated dynamic pool: both
    hosts converge to the same global metrics, and quality statistically
    matches a single-process run on the same data (the reference's
    single-process-oracle strategy, test/ftrl.cc)."""
    rng = np.random.default_rng(3)
    pattern = _learnable_libsvm(tmp_path, rng)
    out = run_mp(2, f"""
        import numpy as np
        from wormhole_tpu.learners.async_sgd import AsyncSGD
        from wormhole_tpu.utils.config import load_config
        cfg = load_config(None, {CFG_COMMON.split()!r} + [
            "train_data={pattern}", "max_data_pass=4",
            "model_out={tmp_path}/mp_model"])
        app = AsyncSGD(cfg)
        prog = app.run()
        pooled = []
        vp = app._multihost_pass(cfg.train_data, "val", pooled)
        pa = app._allreduce_pooled_auc(pooled)
        print(f"OK rank {{app.rt.rank}} num_ex={{prog.num_ex}} "
              f"auc={{pa:.4f}} vacc={{vp.acc / max(vp.count, 1):.4f}}")
    """, timeout=420)
    assert out.count("OK rank") == 2
    rows = [ln for ln in out.splitlines() if "num_ex=" in ln]
    # both hosts computed the same GLOBAL progress and eval metrics
    assert len({ln.split("rank ")[1][2:] for ln in rows}) == 1, out
    num_ex = int(rows[0].split("num_ex=")[1].split()[0])
    assert num_ex == 4 * 800          # every row of every file, each pass
    auc_mp = float(rows[0].split("auc=")[1].split()[0])
    # single-process oracle on the same data (test/ftrl.cc strategy)
    from wormhole_tpu.learners.async_sgd import AsyncSGD
    from wormhole_tpu.utils.config import load_config
    cfg = load_config(None, CFG_COMMON.split() + [
        f"train_data={pattern}", "max_data_pass=4"])
    solo = AsyncSGD(cfg)
    solo.run()
    _, solo_auc = solo._run_eval(pattern)
    assert auc_mp > 0.9, out
    assert abs(auc_mp - solo_auc) < 0.05, (auc_mp, solo_auc)
    # per-host model shards were written
    assert (tmp_path / "mp_model_0").exists()
    assert (tmp_path / "mp_model_1").exists()


def test_mp_async_restart_resumes(tmp_path):
    """Checkpoint every pass; a restarted job resumes from the saved
    version instead of pass 0 (rabit LoadCheckPoint semantics for the
    flagship learner)."""
    rng = np.random.default_rng(4)
    pattern = _learnable_libsvm(tmp_path, rng, n_files=1, rows=200)
    body = f"""
        from wormhole_tpu.learners.async_sgd import AsyncSGD
        from wormhole_tpu.utils.config import load_config
        cfg = load_config(None, {CFG_COMMON.split()!r} + [
            "train_data={pattern}", "max_data_pass=MAXPASS",
            "checkpoint_dir={tmp_path}/ckpt"])
        app = AsyncSGD(cfg)
        prog = app.run()
        print(f"OK rank {{app.rt.rank}} num_ex={{prog.num_ex}}")
    """
    out1 = run_mp(2, body.replace("MAXPASS", "2"), timeout=420)
    assert out1.count("OK rank") == 2
    # "restart": same job continues to 4 passes — must resume at pass 2,
    # training only 2 more passes (num_ex counts post-resume rows)
    out2 = run_mp(2, body.replace("MAXPASS", "4"), timeout=420)
    assert out2.count("OK rank") == 2
    num_ex = parse_num_ex(out2)[0]
    # only passes 2 and 3 ran — the job resumed from the v2 checkpoint
    assert num_ex == 2 * 200, out2


def test_mp_crec2_tile_training_converges(tmp_path):
    """2-process crec2: per-host block shards feed the mesh tile step
    (model table replicated over data:2 across hosts); the planted
    feature is learned and both hosts report identical global metrics."""
    rng = np.random.default_rng(5)
    n, nnz = 4096, 8
    import wormhole_tpu  # noqa: F401  (path check)
    from wormhole_tpu.data.crec import CRec2Writer
    from wormhole_tpu.ops import tilemm
    nb = 2 * tilemm.TILE
    keys = rng.integers(1, 1 << 31, size=(n, nnz), dtype=np.uint32)
    sel = rng.random(n) < 0.5
    keys[sel, 0] = np.uint32(123456)
    keys[~sel, 0] = np.uint32(654321)
    labels = sel.astype(np.uint8)
    path = tmp_path / "mp.crec2"
    with CRec2Writer(str(path), nnz=nnz, nb=nb, subblocks=1) as w:
        w.append(keys, labels)
    out = run_mp(2, f"""
        from wormhole_tpu.learners.async_sgd import AsyncSGD
        from wormhole_tpu.utils.config import load_config
        cfg = load_config(None, [
            "train_data={path}", "data_format=crec2", "num_buckets={nb}",
            "lr_eta=0.5", "max_data_pass=6", "disp_itv=1e12",
            "num_parts_per_file=2"])
        app = AsyncSGD(cfg)
        prog = app.run()
        acc = prog.acc / max(prog.count, 1)
        print(f"OK rank {{app.rt.rank}} num_ex={{prog.num_ex}} "
              f"acc={{acc:.4f}}")
    """, timeout=420)
    assert out.count("OK rank") == 2
    rows = [ln for ln in out.splitlines() if "num_ex=" in ln]
    assert len({ln.split("rank ")[1][2:] for ln in rows}) == 1, out
    acc = float(rows[0].split("acc=")[1].split()[0])
    assert acc > 0.85, out


def test_mp_gbdt_matches_single_process(tmp_path):
    """dsplit=row GBDT: 2 processes each hold half the rows, histograms
    allreduce per level — the trees must be IDENTICAL to a single-process
    run over all rows (same global cuts, same global hists, same
    deterministic split selection)."""
    out = run_mp(2, f"""
        import numpy as np
        from wormhole_tpu.models.gbdt import GBDT, GBDTConfig
        from wormhole_tpu.parallel.mesh import MeshRuntime
        rt = MeshRuntime.create()
        rng = np.random.default_rng(7)         # same stream on both ranks
        x = rng.standard_normal((600, 8)).astype(np.float32)
        y = ((x[:, 0] + 0.5 * x[:, 3] > 0)).astype(np.float32)
        half = x.shape[0] // 2
        sl = slice(0, half) if rt.rank == 0 else slice(half, None)
        model = GBDT(GBDTConfig(num_round=5, max_depth=3), rt)
        model.fit(x[sl], y[sl])
        feats = np.concatenate([np.asarray(t.feature) for t in model.trees])
        sbs = np.concatenate([np.asarray(t.split_bin) for t in model.trees])
        mets = model.evaluate(x[sl], y[sl])
        print(f"OK rank {{rt.rank}} trees="
              f"{{feats.tolist()}}|{{sbs.tolist()}} "
              f"auc={{mets['auc']:.6f}} ll={{model.history[-1]:.8f}}")
    """, timeout=420)
    assert out.count("OK rank") == 2
    rows = [ln for ln in out.splitlines() if "trees=" in ln]
    # both ranks built the same trees and merged metrics
    assert len({ln.split("rank ")[1][2:] for ln in rows}) == 1, out
    # single-process oracle over ALL rows builds the same trees
    from wormhole_tpu.models.gbdt import GBDT, GBDTConfig
    rng = np.random.default_rng(7)
    x = rng.standard_normal((600, 8)).astype(np.float32)
    y = ((x[:, 0] + 0.5 * x[:, 3] > 0)).astype(np.float32)
    solo = GBDT(GBDTConfig(num_round=5, max_depth=3))
    solo.fit(x, y)
    feats = np.concatenate([np.asarray(t.feature) for t in solo.trees])
    sbs = np.concatenate([np.asarray(t.split_bin) for t in solo.trees])
    got_f, got_s = rows[0].split("trees=")[1].split(" auc=")[0].split("|")
    same = (np.array_equal(np.asarray(eval(got_f)), feats)
            and np.array_equal(np.asarray(eval(got_s)), sbs))
    auc_mp = float(rows[0].split("auc=")[1].split()[0])
    if not same:
        # f32 histogram partial-sum ORDER differs between the 8-shard solo
        # scatter and the 2-host allreduce, so a near-tie in gain may
        # legitimately flip a split; then the models must still agree
        # statistically (nodes mostly equal, same quality)
        frac = np.mean(np.asarray(eval(got_f)) == feats)
        assert frac > 0.9, (frac, out)
        assert abs(auc_mp - solo.evaluate(x, y)["auc"]) < 0.01, out
    assert auc_mp > 0.9, out


def test_mp_gbdt_sparse_matches_single_process(tmp_path):
    """dsplit=row SPARSE GBDT (closes VERDICT r4 Missing #1): each process
    loads its CSR shard of a wide libsvm file, feature ids and quantile
    cuts are agreed globally (_global_sparse_sketch), and the per-level
    histogram allreduce makes both ranks build the same trees as a
    single-process fit over all rows — without any (rows, F)
    densification (reference: distributed xgboost on sparse libsvm,
    learn/xgboost/README.md:35-44)."""
    rng = np.random.default_rng(13)
    n, dim = 600, 500
    lines = []
    for _ in range(n):
        y = rng.random() < 0.5
        feats = np.sort(rng.choice(np.arange(2, dim), size=12,
                                   replace=False))
        vals = np.round(rng.standard_normal(12), 3)
        planted = 0 if y else 1
        toks = [f"{planted}:1"] + [f"{j}:{v}" for j, v in zip(feats, vals)]
        lines.append(f"{int(y)} " + " ".join(toks))
    p = tmp_path / "wide.libsvm"
    p.write_text("\n".join(lines) + "\n")
    out = run_mp(2, f"""
        import numpy as np
        from wormhole_tpu.models.gbdt import (GBDT, GBDTConfig,
                                              load_sparse_binned)
        from wormhole_tpu.parallel.mesh import MeshRuntime
        rt = MeshRuntime.create()
        part, nparts = rt.local_part()
        data = load_sparse_binned({str(p)!r}, "libsvm", 16,
                                  part, nparts, runtime=rt)
        model = GBDT(GBDTConfig(num_round=4, max_depth=3, num_bins=16),
                     rt)
        model.fit_sparse(data)
        feats = np.concatenate([np.asarray(t.feature)
                                for t in model.trees])
        sbs = np.concatenate([np.asarray(t.split_bin)
                              for t in model.trees])
        mets = model.evaluate_sparse(data)
        print(f"OK rank {{rt.rank}} trees="
              f"{{feats.tolist()}}|{{sbs.tolist()}} "
              f"auc={{mets['auc']:.6f}}")
    """, timeout=420)
    assert out.count("OK rank") == 2
    rows = [ln for ln in out.splitlines() if "trees=" in ln]
    # both ranks agreed on cuts, hists, and therefore trees
    assert len({ln.split("rank ")[1][2:] for ln in rows}) == 1, out
    # single-process oracle over ALL rows
    from wormhole_tpu.models.gbdt import GBDT, GBDTConfig, \
        load_sparse_binned
    data = load_sparse_binned(str(p), "libsvm", 16)
    solo = GBDT(GBDTConfig(num_round=4, max_depth=3, num_bins=16))
    solo.fit_sparse(data)
    feats = np.concatenate([np.asarray(t.feature) for t in solo.trees])
    sbs = np.concatenate([np.asarray(t.split_bin) for t in solo.trees])
    got_f, got_s = rows[0].split("trees=")[1].split(" auc=")[0].split("|")
    same = (np.array_equal(np.asarray(eval(got_f)), feats)
            and np.array_equal(np.asarray(eval(got_s)), sbs))
    auc_mp = float(rows[0].split("auc=")[1].split()[0])
    if not same:
        # f32 histogram partial-sum order differs between the sharded
        # solo scatter and the 2-host allreduce; near-tie gains may flip
        frac = np.mean(np.asarray(eval(got_f)) == feats)
        assert frac > 0.9, (frac, out)
    assert auc_mp > 0.9, out


def test_mp_kmeans_two_hosts(tmp_path):
    """Each process reads its shard (rank/world), stats allreduce across
    processes — the reference's multi-node-without-a-cluster test."""
    rng = np.random.default_rng(0)
    centers = rng.standard_normal((3, 12))
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    lines = []
    for i in range(240):
        x = centers[i % 3] + 0.05 * rng.standard_normal(12)
        feats = " ".join(f"{j}:{x[j]:.5g}" for j in range(12))
        lines.append(f"0 {feats}")
    data = tmp_path / "km.libsvm"
    data.write_text("\n".join(lines) + "\n")

    out = run_mp(2, f"""
        from wormhole_tpu.models.kmeans import KMeans, KMeansConfig
        from wormhole_tpu.parallel.mesh import MeshRuntime
        rt = MeshRuntime.create()
        km = KMeans(KMeansConfig(num_clusters=3, max_iter=6,
                                 minibatch_size=64), rt)
        batches = km.load_batches({str(data)!r})
        km.fit(batches)
        assert km.history[-1] < 0.05, km.history
        print(f"OK rank {{rt.rank}} objv={{km.history[-1]:.4f}}")
    """)
    assert out.count("OK rank") == 2
    # both processes converged to the same global objective
    objvs = {ln.split("objv=")[1] for ln in out.splitlines()
             if "objv=" in ln}
    assert len(objvs) == 1, out


def test_mp_restarts_resume_after_crash(tmp_path):
    """Fault injection (the reference's tracker-relaunch + rabit restart
    story): rank 1 kills itself mid-training on the first attempt; the
    launcher's --restarts relaunches the whole job, which resumes from
    the last committed checkpoint version instead of pass 0."""
    rng = np.random.default_rng(6)
    pattern = _learnable_libsvm(tmp_path, rng, n_files=1, rows=200)
    marker = tmp_path / "crashed_once"
    body = f"""
        import os, sys
        from wormhole_tpu.learners.async_sgd import AsyncSGD
        from wormhole_tpu.utils.config import load_config
        cfg = load_config(None, {CFG_COMMON.split()!r} + [
            "train_data={pattern}", "max_data_pass=4",
            "checkpoint_dir={tmp_path}/ckpt"])
        app = AsyncSGD(cfg)
        if not os.path.exists("{marker}") and app.rt.rank == 1:
            # crash AFTER pass-2 checkpoints exist: run 2 passes, die
            cfg2 = cfg.merged(["max_data_pass=2"])
            app2 = AsyncSGD(cfg2, app.rt, store=app.store)
            app2.run()
            open("{marker}", "w").close()
            os._exit(17)
        prog = app.run()
        print(f"OK rank {{app.rt.rank}} num_ex={{prog.num_ex}}")
    """
    # generous timeout: under the full suite this test shares the host
    # with other mp tests and has flaked on load (round-3 advisor note)
    r = run_mp(2, body, timeout=900, launcher_args=("--restarts", "2"),
               raw=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "restart 1/2" in r.stderr, r.stderr
    assert marker.exists()
    assert "num_ex=" in r.stdout, (
        "worker never printed its final Progress line:\n"
        f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}")
    # the retry resumed at pass 2: ranks trained only passes 2-3
    num_ex = parse_num_ex(r.stdout)[0]
    assert num_ex == 2 * 200, r.stdout


def test_mp_crec_v1_dense_training_converges(tmp_path):
    """2-process crec v1: per-host block shards feed the mesh dense-apply
    step (data:2 across hosts, on-device key fold + range-sharded
    scatter); the planted feature is learned and both hosts report
    identical global metrics — closes VERDICT r3's 'crec v1 has no
    multi-process path' hole."""
    rng = np.random.default_rng(11)
    n, nnz = 4096, 8
    from wormhole_tpu.data.crec import CRecWriter
    nb = 1 << 16
    keys = rng.integers(1, 1 << 31, size=(n, nnz), dtype=np.uint32)
    sel = rng.random(n) < 0.5
    keys[sel, 0] = np.uint32(123456)
    keys[~sel, 0] = np.uint32(654321)
    labels = sel.astype(np.uint8)
    path = tmp_path / "mp.crec"
    with CRecWriter(str(path), nnz=nnz, block_rows=1024) as w:
        w.append(keys, labels)
    out = run_mp(2, f"""
        from wormhole_tpu.learners.async_sgd import AsyncSGD
        from wormhole_tpu.utils.config import load_config
        cfg = load_config(None, [
            "train_data={path}", "data_format=crec", "num_buckets={nb}",
            "lr_eta=0.5", "max_data_pass=6", "disp_itv=1e12",
            "num_parts_per_file=2"])
        app = AsyncSGD(cfg)
        prog = app.run()
        acc = prog.acc / max(prog.count, 1)
        print(f"OK rank {{app.rt.rank}} num_ex={{prog.num_ex}} "
              f"acc={{acc:.4f}}")
    """, timeout=420)
    assert out.count("OK rank") == 2
    rows = [ln for ln in out.splitlines() if "num_ex=" in ln]
    assert len({ln.split("rank ")[1][2:] for ln in rows}) == 1, out
    acc = float(rows[0].split("acc=")[1].split()[0])
    assert acc > 0.85, out


def test_mp_straggler_reexecution_crec(tmp_path):
    """Deterministic straggler re-execution (VERDICT r3 Weak #4): one
    host's part is 8x the other's (uneven parts — the scenario the
    replicated pool exists for). After the fast host drains, the big
    part crosses the 3x-mean-ROUNDS threshold, is re-issued to the idle
    host WITH a skip count, and the original abandons — every block
    processed exactly once, proven by exact global row accounting."""
    rng = np.random.default_rng(23)
    from wormhole_tpu.data.crec import CRecWriter
    nnz, br = 8, 512
    sizes = {"aa_big": 24 * br, "bb_small": 3 * br}
    for name, n in sizes.items():
        keys = rng.integers(1, 1 << 31, size=(n, nnz), dtype=np.uint32)
        labels = (rng.random(n) < 0.5).astype(np.uint8)
        with CRecWriter(str(tmp_path / f"{name}.crec"), nnz=nnz,
                        block_rows=br) as w:
            w.append(keys, labels)
    total = sum(sizes.values())
    r = run_mp(2, f"""
        from wormhole_tpu.learners.async_sgd import AsyncSGD
        from wormhole_tpu.utils.config import load_config
        cfg = load_config(None, [
            "train_data={tmp_path}/*.crec", "data_format=crec",
            "num_buckets=65536", "lr_eta=0.1", "max_data_pass=1",
            "disp_itv=1e12"])
        app = AsyncSGD(cfg)
        prog = app.run()
        print(f"OK rank {{app.rt.rank}} num_ex={{prog.num_ex}}")
    """, timeout=420, raw=True)
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout
    assert out.count("OK rank") == 2
    # the mechanism actually fired...
    assert "straggler: re-queue" in r.stderr, r.stderr
    assert "abandoning at block" in r.stderr, r.stderr
    # ...and accounting stayed exact: every row of every file once
    rows = [ln for ln in out.splitlines() if "num_ex=" in ln]
    assert len({ln.split("rank ")[1][2:] for ln in rows}) == 1, out
    num_ex = int(rows[0].split("num_ex=")[1].split()[0])
    assert num_ex == total, out


def test_mp_straggler_crash_during_reissue(tmp_path):
    """Straggler x failure interaction (VERDICT r4 Missing #4): the host
    that CLAIMS a re-issued straggler part kills itself at the moment of
    the takeover claim. The launcher's --restarts relaunches the whole
    world, the rebuilt pool re-runs the pass (no checkpoint configured:
    recovery = full-pass re-execution), the straggler re-issue fires
    again, and the job completes with exact global row accounting.
    Reference: failure handler and straggler killer coexisting on live
    pool state, workload_pool.h:111,125-140,169-190."""
    rng = np.random.default_rng(31)
    from wormhole_tpu.data.crec import CRecWriter
    nnz, br = 8, 512
    sizes = {"aa_big": 24 * br, "bb_small": 3 * br}
    for name, n in sizes.items():
        keys = rng.integers(1, 1 << 31, size=(n, nnz), dtype=np.uint32)
        labels = (rng.random(n) < 0.5).astype(np.uint8)
        with CRecWriter(str(tmp_path / f"{name}.crec"), nnz=nnz,
                        block_rows=br) as w:
            w.append(keys, labels)
    total = sum(sizes.values())
    marker = tmp_path / "crashed_once"
    r = run_mp(2, f"""
        import os
        from wormhole_tpu.sched.workload_pool import ReplicatedRounds
        _claimed = ReplicatedRounds.claimed
        def claimed(self, r, wl):
            skip = _claimed(self, r, wl)
            # first straggler takeover: the NEW holder dies mid-claim
            if (r == self.rank and skip > 0
                    and not os.path.exists({str(marker)!r})):
                open({str(marker)!r}, "w").close()
                os._exit(17)
            return skip
        ReplicatedRounds.claimed = claimed
        from wormhole_tpu.learners.async_sgd import AsyncSGD
        from wormhole_tpu.utils.config import load_config
        cfg = load_config(None, [
            "train_data={tmp_path}/*.crec", "data_format=crec",
            "num_buckets=65536", "lr_eta=0.1", "max_data_pass=1",
            "disp_itv=1e12"])
        app = AsyncSGD(cfg)
        prog = app.run()
        print(f"OK rank {{app.rt.rank}} num_ex={{prog.num_ex}}")
    """, timeout=600, launcher_args=("--restarts", "2"), raw=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert marker.exists(), "crash never fired: re-issue claim not seen"
    assert "straggler: re-queue" in r.stderr, r.stderr
    assert "restart 1/2" in r.stderr, r.stderr
    out = r.stdout
    assert out.count("OK rank") == 2
    rows = [ln for ln in out.splitlines() if "num_ex=" in ln]
    assert len({ln.split("rank ")[1][2:] for ln in rows}) == 1, out
    # the post-restart pass processed every row of every file exactly once
    assert parse_num_ex(out)[0] == total, out


def test_mp_straggler_reexecution_sparse(tmp_path):
    """Same straggler handoff through the sparse/text multihost pass:
    minibatch-granular skip, exact row accounting."""
    rng = np.random.default_rng(29)
    for name, rows in (("aa_big", 2400), ("bb_small", 300)):
        lines = []
        for _ in range(rows):
            y = rng.random() < 0.5
            feats = sorted(rng.choice(np.arange(2, 64), size=6,
                                      replace=False))
            toks = [f"{0 if y else 1}:1"] + [f"{j}:1" for j in feats]
            lines.append(f"{int(y)} " + " ".join(toks))
        (tmp_path / f"{name}.libsvm").write_text("\n".join(lines) + "\n")
    r = run_mp(2, f"""
        from wormhole_tpu.learners.async_sgd import AsyncSGD
        from wormhole_tpu.utils.config import load_config
        cfg = load_config(None, {CFG_COMMON.split()!r} + [
            "train_data={tmp_path}/*.libsvm", "max_data_pass=1"])
        app = AsyncSGD(cfg)
        prog = app.run()
        print(f"OK rank {{app.rt.rank}} num_ex={{prog.num_ex}}")
    """, timeout=420, raw=True)
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout
    assert out.count("OK rank") == 2
    assert "straggler: re-queue" in r.stderr, r.stderr
    assert "abandoning at block" in r.stderr, r.stderr
    rows = [ln for ln in out.splitlines() if "num_ex=" in ln]
    assert len({ln.split("rank ")[1][2:] for ln in rows}) == 1, out
    num_ex = int(rows[0].split("num_ex=")[1].split()[0])
    assert num_ex == 2700, out


def test_mp_trace_merge_and_skew_report(tmp_path):
    """--trace-dir end to end (PR-6): both ranks trace into the exported
    directory via the obs.setup env fallback, rank 1 arrives late at
    every sited collective, and the launcher's exit-time merge produces
    one merged Perfetto trace plus a skew report naming rank 1 with its
    per-collective lateness."""
    import json
    trace_dir = tmp_path / "traces"
    hb_dir = tmp_path / "hb"
    r = run_mp(2, """
        import time
        import numpy as np
        from wormhole_tpu.parallel.mesh import MeshRuntime
        from wormhole_tpu import obs
        from wormhole_tpu.parallel.collectives import allreduce_tree
        from wormhole_tpu.utils.config import Config
        rt = MeshRuntime.create()
        hub = obs.setup(Config(), rank=rt.rank)
        # both launcher env fallbacks picked up: heartbeat + trace dirs
        assert hub.active and hub.export_dir, "env fallbacks missing"
        from wormhole_tpu.obs import trace as _t
        assert _t.enabled(), "trace env fallback missing"
        hub.heartbeat_tick(step=0, num_ex=0)
        for i in range(4):
            if rt.rank == 1:
                time.sleep(0.1)        # the planted straggler
            total = allreduce_tree(np.asarray(float(rt.rank + 1)),
                                   rt.mesh, "sum", site="test/step")
            assert float(total) == 3.0, total
        hub.finalize(step=4, num_ex=400, wall_s=1.0)
        print(f"OK rank {rt.rank}")
    """, launcher_args=("--heartbeat-dir", str(hb_dir),
                        "--trace-dir", str(trace_dir)), raw=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("OK rank") == 2

    # per-rank trace files + the merged artifacts exist
    assert (trace_dir / "trace.json").exists()
    assert (trace_dir / "trace.r1.json").exists()
    assert (trace_dir / "merged.trace.json").exists()
    assert (trace_dir / "skew_report.json").exists()

    report = json.load(open(trace_dir / "skew_report.json"))
    assert report["ranks"] == [0, 1]
    assert report["clock_source"] == "heartbeat"
    assert report["collectives_matched"] >= 3
    # the delayed rank is named, last in (nearly) every collective,
    # ~100 ms late each time
    w = report["worst"]
    assert w["rank"] == 1, report
    assert w["last_in"] >= report["collectives_matched"] - 1, report
    assert w["lateness_ms"] > 50 * w["last_in"], report
    assert report["sites"]["test/step"]["max_skew_ms"] > 50, report

    # the merged doc carries both ranks' events on one timeline
    merged = json.load(open(trace_dir / "merged.trace.json"))
    assert merged["metadata"]["merged"] is True
    pids = {e.get("pid") for e in merged["traceEvents"]
            if e.get("ph") == "X"}
    assert {0, 1} <= pids, pids

    # and the launcher printed the attribution lines
    assert "merged trace:" in r.stderr, r.stderr
    assert "collective skew: w1" in r.stderr, r.stderr


def test_mp_trace_merge_without_jax_distributed(tmp_path):
    """The exit-time merge, backend-independent: workers skip
    jax.distributed (no CPU multiprocess collectives needed) and record
    sited collective spans on the single-process fast path — the span
    boundary and (site, seq) stamping are identical. Rank 1 sleeps
    before every collective, so the launcher-side merge must name it
    with growing per-collective lateness."""
    import json
    trace_dir = tmp_path / "traces"
    hb_dir = tmp_path / "hb"
    r = run_mp(2, """
        import os, time
        import numpy as np
        from wormhole_tpu import obs
        from wormhole_tpu.obs import trace
        from wormhole_tpu.obs.metrics import Registry
        from wormhole_tpu.parallel.collectives import allreduce_tree
        from wormhole_tpu.utils.config import Config
        rank = int(os.environ["PROCESS_ID"])
        hub = obs.setup(Config(), rank=rank, registry=Registry())
        assert hub.active and trace.enabled(), "env fallbacks missing"
        hub.heartbeat_tick(step=0, num_ex=0)
        for i in range(4):
            if rank == 1:
                time.sleep(0.1)            # the planted straggler
            allreduce_tree(np.asarray(1.0), None, "sum",
                           site="test/step")
        hub.finalize(step=4, num_ex=400, wall_s=1.0)
        print(f"OK rank {rank}")
    """, launcher_args=("--heartbeat-dir", str(hb_dir),
                        "--trace-dir", str(trace_dir)), raw=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("OK rank") == 2

    assert (trace_dir / "trace.json").exists()
    assert (trace_dir / "trace.r1.json").exists()
    assert (trace_dir / "merged.trace.json").exists()
    report = json.load(open(trace_dir / "skew_report.json"))
    assert report["ranks"] == [0, 1]
    assert report["clock_source"] == "heartbeat"
    assert report["collectives_matched"] == 4
    w = report["worst"]
    assert w["rank"] == 1, report
    # cumulative sleeps: rank 1 trails by ~100*k ms at the k-th
    # collective; spawn skew between the two children is far smaller
    assert w["lateness_ms"] > 300, report
    # JSON object keys are strings on disk
    assert report["per_rank"]["1"]["last_in"] >= 3, report
    assert report["sites"]["test/step"]["max_skew_ms"] > 100, report
    merged = json.load(open(trace_dir / "merged.trace.json"))
    pids = {e.get("pid") for e in merged["traceEvents"]
            if e.get("ph") == "X"}
    assert {0, 1} <= pids, pids
    assert "merged trace:" in r.stderr, r.stderr
    assert "collective skew: w1" in r.stderr, r.stderr


def test_mp_socket_wire_trace_merge(tmp_path):
    """The trace-merge drill with REAL cross-rank exchange and no
    jax.distributed (runs in every environment): children peer over
    the TCP wire (SocketWire loopback, built from the launcher's
    PROCESS_ID/NUM_PROCESSES exports), run sited allreduces through
    the full transport stack, and rank 1's planted lateness lands in
    the launcher's exit-time skew report exactly as over the jax
    wire — while the allreduce RESULT proves real cross-rank bytes,
    which the single-process fast-path variant above cannot."""
    import json
    trace_dir = tmp_path / "traces"
    hb_dir = tmp_path / "hb"
    rdv = tmp_path / "rdv"
    r = run_mp(2, f"""
        import os, time
        import numpy as np
        from wormhole_tpu import obs
        from wormhole_tpu.obs import trace
        from wormhole_tpu.obs.metrics import Registry
        from wormhole_tpu.parallel.socket_wire import SocketWire
        from wormhole_tpu.parallel.transport import TransportStack
        from wormhole_tpu.utils.config import Config
        rank = int(os.environ["PROCESS_ID"])
        hub = obs.setup(Config(), rank=rank, registry=Registry())
        assert hub.active and trace.enabled(), "env fallbacks missing"
        hub.heartbeat_tick(step=0, num_ex=0)
        stack = TransportStack(wire=SocketWire(rendezvous={str(rdv)!r}))
        for i in range(4):
            if rank == 1:
                time.sleep(0.1)            # the planted straggler
            total = stack.allreduce(np.asarray(float(rank + 1)), None,
                                    op="sum", site="test/step")
            assert float(total) == 3.0, total   # real 2-rank sum
        stack.sync("done")
        hub.finalize(step=4, num_ex=400, wall_s=1.0)
        stack.wire.close()
        print(f"OK rank {{rank}}")
    """, launcher_args=("--heartbeat-dir", str(hb_dir),
                        "--trace-dir", str(trace_dir)), raw=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("OK rank") == 2

    assert (trace_dir / "merged.trace.json").exists()
    report = json.load(open(trace_dir / "skew_report.json"))
    assert report["ranks"] == [0, 1]
    assert report["clock_source"] == "heartbeat"
    assert report["collectives_matched"] == 4
    w = report["worst"]
    assert w["rank"] == 1, report
    # cumulative sleeps: rank 1 trails by ~100*k ms at the k-th
    # collective (arrival skew survives the socket hop unchanged)
    assert w["lateness_ms"] > 300, report
    assert report["sites"]["test/step"]["max_skew_ms"] > 100, report
    assert "collective skew: w1" in r.stderr, r.stderr


def test_mp_socket_wire_supervised_drill(tmp_path):
    """Supervised PEER_LOST drill over the TCP wire: rank 1 dies
    mid-program on the first attempt, rank 0's wire DETECTS the
    disconnect (no timeout wait) and takes the watchdog's PEER_LOST
    exit, the launcher's --restarts relaunches the world, and the
    retry completes over a fresh per-attempt mesh."""
    marker = tmp_path / "crashed_once"
    rdv = tmp_path / "rdv"
    body = f"""
        import os
        import numpy as np
        from wormhole_tpu.ft import watchdog
        from wormhole_tpu.parallel.socket_wire import SocketWire
        from wormhole_tpu.parallel.transport import TransportStack
        rank = int(os.environ["PROCESS_ID"])
        watchdog.configure(60.0)
        # per-attempt rendezvous dir: the retry must not dial attempt
        # 1's dead ports out of a stale committed peer table
        rdv = os.path.join({str(rdv)!r}, os.environ["WORMHOLE_ATTEMPT"])
        stack = TransportStack(wire=SocketWire(rendezvous=rdv))
        stack.sync("mesh_up")
        if rank == 1 and not os.path.exists({str(marker)!r}):
            open({str(marker)!r}, "w").close()
            os._exit(17)                   # die mid-program
        total = stack.allreduce(np.asarray(float(rank + 1)), None,
                                op="sum", site="drill/step")
        assert float(total) == 3.0, total
        stack.wire.close()
        print(f"OK rank {{rank}}")
    """
    r = run_mp(2, body, timeout=240, launcher_args=("--restarts", "2"),
               raw=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert marker.exists(), "crash never fired"
    # rank 0 did not wait out a timeout: the wire detected the loss
    # and surfaced it through the watchdog taxonomy
    assert "peer rank 1 lost" in r.stderr, r.stderr
    assert "restart 1/2" in r.stderr, r.stderr
    assert r.stdout.count("OK rank") == 2

"""Launcher multi-process mode: real jax.distributed over localhost (the
DCN code path the reference exercises with dmlc_local.py multi-process
runs, SURVEY.md §4.3)."""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_mp(n: int, body: str, timeout=240) -> str:
    script = os.path.join(REPO, ".pytest_cache", f"mp_body_{os.getpid()}.py")
    os.makedirs(os.path.dirname(script), exist_ok=True)
    with open(script, "w") as f:
        f.write(textwrap.dedent(body))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS",)}  # children get their own device count
    r = subprocess.run(
        [sys.executable, "-m", "wormhole_tpu.parallel.launcher",
         "-n", str(n), "--cluster", "mp", "--", sys.executable, script],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    return r.stdout


def test_mp_collectives():
    out = run_mp(2, """
        from wormhole_tpu.parallel.mesh import MeshRuntime
        import numpy as np
        rt = MeshRuntime.create()
        assert rt.world == 2, rt.world
        from wormhole_tpu.parallel.collectives import (allreduce_tree,
                                                       broadcast_tree)
        total = allreduce_tree(np.asarray(float(rt.rank + 1)),
                               rt.mesh, "sum")
        assert float(total) == 3.0, total
        mx = allreduce_tree(np.asarray(float(rt.rank)), rt.mesh, "max")
        assert float(mx) == 1.0, mx
        root = broadcast_tree(
            np.asarray(42.0 if rt.rank == 0 else -1.0), rt.mesh)
        assert float(root) == 42.0, root
        print(f"OK rank {rt.rank}")
    """)
    assert out.count("OK rank") == 2


def test_mp_kmeans_two_hosts(tmp_path):
    """Each process reads its shard (rank/world), stats allreduce across
    processes — the reference's multi-node-without-a-cluster test."""
    import numpy as np
    rng = np.random.default_rng(0)
    centers = rng.standard_normal((3, 12))
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    lines = []
    for i in range(240):
        x = centers[i % 3] + 0.05 * rng.standard_normal(12)
        feats = " ".join(f"{j}:{x[j]:.5g}" for j in range(12))
        lines.append(f"0 {feats}")
    data = tmp_path / "km.libsvm"
    data.write_text("\n".join(lines) + "\n")

    out = run_mp(2, f"""
        from wormhole_tpu.models.kmeans import KMeans, KMeansConfig
        from wormhole_tpu.parallel.mesh import MeshRuntime
        rt = MeshRuntime.create()
        km = KMeans(KMeansConfig(num_clusters=3, max_iter=6,
                                 minibatch_size=64), rt)
        batches = km.load_batches({str(data)!r})
        km.fit(batches)
        assert km.history[-1] < 0.05, km.history
        print(f"OK rank {{rt.rank}} objv={{km.history[-1]:.4f}}")
    """)
    assert out.count("OK rank") == 2
    # both processes converged to the same global objective
    objvs = {ln.split("objv=")[1] for ln in out.splitlines()
             if "objv=" in ln}
    assert len(objvs) == 1, out

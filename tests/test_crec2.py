"""crec2 tile-grouped format + the tile-matmul training path.

Mirrors the v1 crec tests (test_crec.py) plus the key new property: the
crec2/tilemm path must train the SAME model as the v1 crec dense-apply
path (both fold keys with hashing.fold_keys32), up to the tile kernels'
bf16 value quantization.
"""

import jax
import numpy as np
import pytest

from wormhole_tpu.data.crec import (CRec2Writer, CRecWriter, PackedFeed,
                                    block2_views, iter_packed2,
                                    read_header2)
from wormhole_tpu.data.hashing import fold_keys32
from wormhole_tpu.ops import tilemm

NB = 2 * tilemm.TILE
NNZ = 8


def write_file(path, keys, labels, **kw):
    kw.setdefault("subblocks", 4)
    kw.setdefault("cap", 16384)
    with CRec2Writer(str(path), nnz=NNZ, nb=NB, **kw) as w:
        w.append(keys, labels)


def make_rows(rng, n):
    keys = rng.integers(0, 1 << 32, size=(n, NNZ), dtype=np.uint32)
    keys[keys == 0xFFFFFFFF] = 0
    keys[rng.random((n, NNZ)) < 0.1] = 0xFFFFFFFF  # missing slots
    labels = (rng.random(n) < 0.4).astype(np.uint8)
    return keys, labels


def test_roundtrip_pairs(tmp_path, rng):
    n = 3000
    keys, labels = make_rows(rng, n)
    path = tmp_path / "a.crec2"
    write_file(path, keys, labels)
    info = read_header2(str(path))
    assert info.total_rows == n
    assert info.num_blocks == 1
    blocks = list(iter_packed2(str(path)))
    assert len(blocks) == 1
    views, rows = blocks[0]
    assert rows == n
    # decode all pairs back to (bucket, row) and compare multisets
    spec = info.spec
    pw = views["pw"].reshape(spec.tiles, spec.subblocks, spec.cap)
    bt, rt, pad = tilemm.unpack_fields(pw)
    got = []
    for t in range(spec.tiles):
        for s in range(spec.subblocks):
            live = ~pad[t, s]
            b = t * tilemm.TILE + bt[t, s][live].astype(np.int64)
            r = s * tilemm.RSUB + rt[t, s][live].astype(np.int64)
            got += list(zip(b.tolist(), r.tolist()))
    rr, cc = np.nonzero(keys != np.uint32(0xFFFFFFFF))
    want = sorted(zip(fold_keys32(keys[rr, cc], NB).tolist(), rr.tolist()))
    assert sorted(got) == want
    # labels: real rows then PAD_LABEL padding
    lab = views["labels"]
    assert np.array_equal(lab[:n], labels)
    assert np.all(lab[n:] == 255)


def test_part_ownership(tmp_path, rng):
    """Part k of n owns a contiguous block range; parts partition the
    file (InputSplit semantics)."""
    n = 2 * 4 * tilemm.RSUB + 17    # 3 blocks (subblocks=4)
    keys, labels = make_rows(rng, n)
    path = tmp_path / "b.crec2"
    write_file(path, keys, labels, cap=33024)
    info = read_header2(str(path))
    assert info.num_blocks == 3
    seen = []
    for part in range(2):
        for _views, rows in iter_packed2(str(path), part, 2):
            seen.append(rows)
    assert sum(seen) == n and len(seen) == 3


def test_feed_cache_replays(tmp_path, rng):
    keys, labels = make_rows(rng, 1000)
    path = tmp_path / "c.crec2"
    write_file(path, keys, labels)
    feed = PackedFeed(str(path), fmt="crec2", cache=True)
    first = [id(d["pw"]) for d, _h, _r in feed]
    assert feed._cache_full
    second = [id(d["pw"]) for d, _h, _r in feed]
    assert first == second            # same device buffers replayed
    assert feed.bytes_read == read_header2(str(path)).block_bytes


def _train(tmp_path, rng, fmt, keys, labels, passes=3):
    from wormhole_tpu.learners.async_sgd import AsyncSGD
    from wormhole_tpu.utils.config import Config
    path = tmp_path / f"train.{fmt}"
    if fmt == "crec2":
        write_file(path, keys, labels)
    else:
        with CRecWriter(str(path), nnz=NNZ, block_rows=4 * tilemm.RSUB) as w:
            w.append(keys, labels)
    cfg = Config(train_data=str(path), data_format=fmt, num_buckets=NB,
                 lr_eta=0.5, max_data_pass=passes, disp_itv=1e12,
                 max_delay=1)
    app = AsyncSGD(cfg)
    app.run()
    return app


def test_crec2_learns_and_matches_v1(tmp_path, rng):
    """FTRL over crec2 converges, and its weights match the v1 crec
    dense-apply path trained on the same rows (same key fold; bf16
    kernel tolerance)."""
    n = 4000
    keys, labels = make_rows(rng, n)
    # make labels learnable: one planted key decides the label
    planted = np.uint32(123456)
    sel = rng.random(n) < 0.5
    keys[sel, 0] = planted
    keys[~sel, 0] = np.uint32(654321)
    labels = sel.astype(np.uint8)
    app2 = _train(tmp_path, rng, "crec2", keys, labels, passes=6)
    prog = app2.progress
    assert prog.num_ex == 6 * n
    # mean per-pass accuracy includes the untrained first pass
    assert prog.acc / max(prog.count, 1) > 0.85
    app1 = _train(tmp_path, rng, "crec", keys, labels, passes=6)
    w2 = np.asarray(app2.store.handle.weights(app2.store.slots))
    w1 = np.asarray(app1.store.handle.weights(app1.store.slots))
    live = (np.abs(w1) > 1e-6) | (np.abs(w2) > 1e-6)
    assert live.any()
    assert np.allclose(w1[live], w2[live], rtol=0.05, atol=5e-3)


def test_writer_rejects_skew_overflow(tmp_path, rng):
    """Beyond-ovf_cap skew raises loudly instead of dropping pairs."""
    n = 2000
    keys = np.full((n, NNZ), np.uint32(42), np.uint32)  # one hot bucket
    labels = np.zeros(n, np.uint8)
    with pytest.raises(ValueError, match="overflow"):
        write_file(tmp_path / "d.crec2", keys, labels, cap=128, ovf_cap=128)


def test_crec2_mesh_training_converges(tmp_path, rng):
    """AsyncSGD over crec2 on a data:2,model:2 mesh (the shard_map tile
    step): learns the planted feature like the single-device path."""
    n = 4000
    keys, labels = make_rows(rng, n)
    sel = rng.random(n) < 0.5
    keys[sel, 0] = np.uint32(123456)
    keys[~sel, 0] = np.uint32(654321)
    labels = sel.astype(np.uint8)
    from wormhole_tpu.learners.async_sgd import AsyncSGD
    from wormhole_tpu.utils.config import Config
    path = tmp_path / "mesh.crec2"
    write_file(path, keys, labels)
    import jax
    from wormhole_tpu.parallel.mesh import MeshRuntime, make_mesh
    cfg = Config(train_data=str(path), data_format="crec2", num_buckets=NB,
                 lr_eta=0.5, max_data_pass=6, disp_itv=1e12, max_delay=1)
    rt = MeshRuntime.create()
    rt.mesh = make_mesh("data:2,model:2", jax.devices()[:4])
    app = AsyncSGD(cfg, rt)
    prog = app.run()
    assert prog.num_ex == 6 * n
    assert prog.acc / max(prog.count, 1) > 0.85


def test_crec2_metric_accounting_exact(tmp_path, rng):
    """The on-device metric accumulator + async ticket pipeline credits
    every step exactly once across mid-stream (non-final) drains, cached
    replay windows, and the final flush: num_ex == rows x passes, count
    == steps, and accuracy stays a mean over steps."""
    from wormhole_tpu.learners.async_sgd import AsyncSGD
    from wormhole_tpu.utils.config import Config

    n = 2 * 4 * tilemm.RSUB + 100          # 3 blocks, padded tail
    keys, labels = make_rows(rng, n)
    keys[rng.random((n, NNZ)) < 0.9] = 0xFFFFFFFF   # sparse rows: small cap
    # keep every row non-empty with a fresh uniform key (a shared
    # constant would be exactly the hot-bucket skew the cap rejects)
    keys[:, 0] = rng.integers(1, 1 << 32, size=n, dtype=np.uint32)
    path = tmp_path / "acct.crec2"
    write_file(path, keys, labels, cap=8192, ovf_cap=4096)
    cfg = Config(train_data=str(path), data_format="crec2", num_buckets=NB,
                 lr_eta=0.5, max_data_pass=1, disp_itv=0.0,  # drain often
                 max_delay=2, cache_device=True)
    app = AsyncSGD(cfg)
    passes = 5
    num_ex = count = 0
    objv_sum = 0.0
    # tiny drain window so replay passes hit the mid-stream ticket path
    # (instance attribute: must not leak into other tests' AsyncSGDs)
    app.CREC_DRAIN_CHUNK = 2
    for _ in range(passes):
        prog = app.process(str(path), 0, 1)
        num_ex += prog.num_ex
        count += prog.count
        objv_sum += prog.objv
    tail = app.flush_metrics()
    num_ex += tail.num_ex
    count += tail.count
    objv_sum += tail.objv
    assert num_ex == passes * n            # padded rows not credited
    # one credit per dispatched step: under a data-parallel mesh the 3
    # blocks ride in ceil(3/D) grouped steps, single-device in 3
    D = max(app.rt.data_axis_size, 1)
    assert count == passes * -(-3 // D)
    assert np.isfinite(objv_sum) and objv_sum > 0
    assert not app._crec_tickets and app._crec_count == 0


def test_crec2_adagrad_l1_learns(tmp_path, rng):
    """The tile path with a non-identity-on-zero-grad handle (AdaGrad +
    L1): the touched-bucket mask keeps untouched buckets frozen, so the
    planted feature is learned instead of being prox-shrunk away every
    sweep."""
    from wormhole_tpu.learners.async_sgd import AsyncSGD
    from wormhole_tpu.utils.config import Config

    n = 4000
    keys, labels = make_rows(rng, n)
    sel = rng.random(n) < 0.5
    keys[sel, 0] = np.uint32(123456)
    keys[~sel, 0] = np.uint32(654321)
    labels = sel.astype(np.uint8)
    path = tmp_path / "ada.crec2"
    write_file(path, keys, labels)
    cfg = Config(train_data=str(path), data_format="crec2", num_buckets=NB,
                 lr_eta=0.5, max_data_pass=6, disp_itv=1e12, max_delay=1)
    cfg.algo = type(cfg.algo)("adagrad")
    cfg.lambda_ = [0.1, 0.01]
    app = AsyncSGD(cfg)
    app.run()
    prog = app.progress
    assert prog.num_ex == 6 * n
    assert prog.acc / max(prog.count, 1) > 0.8
    # untouched buckets stayed exactly at init (zero): the L1 prox never
    # swept them, and touched weights are nonzero
    w = np.asarray(app.store.handle.weights(app.store.slots))
    assert app.store.nnz_weight() > 0
    assert np.count_nonzero(w) < NB  # the sweep did not touch everything


def test_crec2_predict_task(tmp_path, rng):
    """test_data + pred_out over crec2 (the tile eval path feeding the
    pooled predict writer): one sigma(margin) per real row, in file
    order, padded tail rows excluded."""
    from wormhole_tpu.learners.async_sgd import AsyncSGD
    from wormhole_tpu.ops.metrics import auc_np
    from wormhole_tpu.utils.config import Config

    n = 3000
    keys, labels = make_rows(rng, n)
    sel = rng.random(n) < 0.5
    keys[sel, 0] = np.uint32(123456)
    keys[~sel, 0] = np.uint32(654321)
    labels = sel.astype(np.uint8)
    path = tmp_path / "p.crec2"
    write_file(path, keys, labels)
    pred = str(tmp_path / "preds.txt")
    cfg = Config(train_data=str(path), test_data=str(path), pred_out=pred,
                 data_format="crec2", num_buckets=NB, lr_eta=0.5,
                 max_data_pass=4, disp_itv=1e12, max_delay=1)
    app = AsyncSGD(cfg)
    app.run()
    probs = np.array([float(x) for x in open(pred).read().split()])
    assert len(probs) == n                 # padded rows not predicted
    assert ((probs >= 0) & (probs <= 1)).all()
    assert auc_np(labels.astype(np.float64), probs) > 0.9


def test_restore_drops_stale_metric_accumulator(tmp_path, rng):
    """Checkpoint restore must not credit pre-restore steps: the
    on-device metric accumulator is dropped with the rest of the
    transient device state."""
    import jax.numpy as jnp
    from wormhole_tpu.learners.handles import FTRLHandle, LearnRate
    from wormhole_tpu.learners.store import ShardedStore, StoreConfig
    from wormhole_tpu.ops.penalty import L1L2
    from wormhole_tpu.data.crec import CRec2Info

    spec_nb = 2 * tilemm.TILE
    spec = tilemm.make_spec(spec_nb, subblocks=4, cap=1024)
    info = CRec2Info(nnz=NNZ, block_rows=spec.block_rows,
                     total_rows=spec.block_rows, nb=spec_nb,
                     subblocks=4, cap=spec.cap, ovf_cap=0)
    store = ShardedStore(StoreConfig(num_buckets=spec_nb, loss="logit"),
                         FTRLHandle(penalty=L1L2(0.1, 0.01),
                                    lr=LearnRate(0.5, 1.0)))
    buckets = rng.integers(0, spec_nb, size=5000, dtype=np.int64)
    rows = rng.integers(0, spec.block_rows, size=5000).astype(np.int64)
    pw, ovb, _ = tilemm.encode_block(buckets, rows, spec)
    assert not len(ovb)
    labels = (rng.random(spec.block_rows) < 0.4).astype(np.uint8)
    block = {"pw": jnp.asarray(pw), "labels": jnp.asarray(labels)}
    snap = jax.tree_util.tree_map(np.asarray, store.state_pytree())
    store.tile_train_step(block, info)
    store.restore_pytree(snap)           # rewind: the step never happened
    row = store.fetch_metrics()
    assert row[1] == 0.0                 # no rows credited
    store.tile_train_step(block, info)
    row = store.fetch_metrics()
    assert row[1] == float(spec.block_rows)


def test_cross_format_warm_start_raises(tmp_path, rng):
    """A model saved under the text key fold (splitmix64) must refuse a
    crec2 warm start (mix32): the two schemes bucket every feature
    differently, so a silent load would remap the whole model."""
    from wormhole_tpu.learners.handles import FTRLHandle
    from wormhole_tpu.learners.store import ShardedStore, StoreConfig

    store = ShardedStore(StoreConfig(num_buckets=64), FTRLHandle())
    # plant one nonzero weight (slot 0) so the dump has data lines
    store.slots = store.slots.at[3, 0].set(-1.0)
    path = str(tmp_path / "model.txt")
    store.save_model(path, rank=0, key_fold="splitmix64")
    with pytest.raises(ValueError, match="key_fold"):
        store.load_model(path, expect_key_fold="mix32")
    store.load_model(path, expect_key_fold="splitmix64")  # same fold: OK


def test_crec_v1_mesh_training_converges(tmp_path, rng):
    """AsyncSGD over crec v1 on a data:2,model:2 mesh (the shard_map
    dense-apply step): learns the planted feature like the single-device
    v1 path — the distributed hole VERDICT r3 flagged."""
    n = 4000
    keys, labels = make_rows(rng, n)
    sel = rng.random(n) < 0.5
    keys[sel, 0] = np.uint32(123456)
    keys[~sel, 0] = np.uint32(654321)
    labels = sel.astype(np.uint8)
    from wormhole_tpu.learners.async_sgd import AsyncSGD
    from wormhole_tpu.utils.config import Config
    path = tmp_path / "mesh.crec"
    with CRecWriter(str(path), nnz=NNZ, block_rows=1024) as w:
        w.append(keys, labels)
    import jax
    from wormhole_tpu.parallel.mesh import MeshRuntime, make_mesh
    cfg = Config(train_data=str(path), data_format="crec", num_buckets=NB,
                 lr_eta=0.5, max_data_pass=6, disp_itv=1e12, max_delay=1)
    rt = MeshRuntime.create()
    rt.mesh = make_mesh("data:2,model:2", jax.devices()[:4])
    app = AsyncSGD(cfg, rt)
    prog = app.run()
    assert prog.num_ex == 6 * n
    assert prog.acc / max(prog.count, 1) > 0.85


def test_crec_v1_mesh_matches_single_device(tmp_path, rng):
    """v1 mesh dense-apply weights match the single-device v1 step on
    identical rows (exact semantics: same fold, same handle updates —
    only the step grouping differs)."""
    n = 2048
    keys, labels = make_rows(rng, n)
    sel = rng.random(n) < 0.5
    keys[sel, 0] = np.uint32(123456)
    keys[~sel, 0] = np.uint32(654321)
    labels = sel.astype(np.uint8)
    from wormhole_tpu.learners.async_sgd import AsyncSGD
    from wormhole_tpu.utils.config import Config
    import jax
    from wormhole_tpu.parallel.mesh import MeshRuntime, make_mesh
    path = tmp_path / "ab.crec"
    with CRecWriter(str(path), nnz=NNZ, block_rows=512) as w:
        w.append(keys, labels)

    def train(mesh_spec):
        cfg = Config(train_data=str(path), data_format="crec",
                     num_buckets=NB, lr_eta=0.5, max_data_pass=2,
                     disp_itv=1e12, max_delay=1)
        rt = MeshRuntime.create()
        if mesh_spec:
            rt.mesh = make_mesh(mesh_spec, jax.devices()[:4])
        else:
            rt.mesh = make_mesh("data:1", jax.devices()[:1])
        app = AsyncSGD(cfg, rt)
        app.run()
        return np.asarray(app.store.handle.weights(
            app.store.slots.astype(np.float32)))

    w_single = train(None)
    # model:4 keeps the per-step geometry identical (one block per step;
    # D=1), so range-sharding the table must be EXACT up to f32 reorder.
    # (data:K instead groups K blocks into one handle update — a batch-
    # size change, covered by the convergence test above.)
    w_mesh = train("data:1,model:4")
    live = (np.abs(w_single) > 1e-6) | (np.abs(w_mesh) > 1e-6)
    assert live.any()
    assert np.allclose(w_single[live], w_mesh[live], rtol=1e-4, atol=1e-5)

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wormhole_tpu.ops.loss import create_loss
from wormhole_tpu.ops.metrics import accuracy, auc, logloss
from wormhole_tpu.ops.penalty import L1L2
from wormhole_tpu.ops.spmv import spmv_times, spmv_trans_times


def _rand_batch(rng, mb=16, nnz=8, k=40):
    cols = rng.integers(0, k, (mb, nnz)).astype(np.int32)
    vals = rng.normal(size=(mb, nnz)).astype(np.float32)
    vals[rng.random((mb, nnz)) < 0.3] = 0  # padding-like zeros
    return cols, vals


def test_spmv_matches_dense(rng):
    # reference spmv_test.cc: multi-thread vs 1-thread; here device vs numpy
    cols, vals = _rand_batch(rng)
    w = rng.normal(size=40).astype(np.float32)
    got = np.asarray(spmv_times(jnp.asarray(cols), jnp.asarray(vals),
                                jnp.asarray(w)))
    expect = np.einsum("bn,bn->b", vals, w[cols])
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_spmv_trans_matches_scatter(rng):
    cols, vals = _rand_batch(rng)
    dual = rng.normal(size=16).astype(np.float32)
    got = np.asarray(spmv_trans_times(jnp.asarray(cols), jnp.asarray(vals),
                                      jnp.asarray(dual), 40))
    expect = np.zeros(40, np.float32)
    for b in range(16):
        for j in range(8):
            expect[cols[b, j]] += vals[b, j] * dual[b]
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)


def test_spmv_adjoint(rng):
    # <Xw, d> == <w, X^T d>
    cols, vals = _rand_batch(rng)
    w = rng.normal(size=40).astype(np.float32)
    d = rng.normal(size=16).astype(np.float32)
    lhs = float(spmv_times(cols, vals, w) @ d)
    rhs = float(w @ spmv_trans_times(cols, vals, d, 40))
    assert lhs == pytest.approx(rhs, rel=1e-4)


def test_l1l2_prox_golden():
    # penalty.h:36-41 semantics
    p = L1L2(lambda1=1.0, lambda2=0.5)
    z = jnp.asarray([3.0, -3.0, 0.5, -0.5, 0.0])
    eta = jnp.asarray(1.5)
    got = np.asarray(p.solve(z, eta))
    np.testing.assert_allclose(got, [2.0 / 2.0, -2.0 / 2.0, 0, 0, 0])


@pytest.mark.parametrize("name", ["logit", "square_hinge", "square"])
def test_loss_dual_is_gradient(name, rng):
    # dual == d objv / d margin, verified by autodiff
    objv_fn, dual_fn = create_loss(name)
    m = jnp.asarray(rng.normal(size=12).astype(np.float32))
    y = jnp.asarray((rng.random(12) < 0.5).astype(np.float32))
    mask = jnp.asarray((rng.random(12) < 0.8).astype(np.float32))
    auto = jax.grad(lambda mm: objv_fn(mm, y, mask))(m)
    np.testing.assert_allclose(np.asarray(dual_fn(m, y, mask)),
                               np.asarray(auto), rtol=1e-4, atol=1e-5)


def test_auc_golden():
    # hand case: perfect ranking -> 1.0; inverted -> 0.0
    y = jnp.asarray([1.0, 1, 0, 0])
    mask = jnp.ones(4)
    assert float(auc(y, jnp.asarray([4.0, 3, 2, 1]), mask)) == pytest.approx(1.0)
    assert float(auc(y, jnp.asarray([1.0, 2, 3, 4]), mask)) == pytest.approx(0.0)
    # half right
    assert float(auc(y, jnp.asarray([4.0, 1, 3, 2]), mask)) == pytest.approx(0.5)


def test_auc_masked_rows_ignored():
    y = jnp.asarray([1.0, 1, 0, 0, 0, 1])
    m = jnp.asarray([4.0, 3, 2, 1, 99, -99])
    mask = jnp.asarray([1.0, 1, 1, 1, 0, 0])
    assert float(auc(y, m, mask)) == pytest.approx(1.0)


def test_accuracy_and_logloss():
    y = jnp.asarray([1.0, 0, 1, 0])
    m = jnp.asarray([2.0, -2, -1, 1])
    mask = jnp.ones(4)
    assert float(accuracy(y, m, mask)) == pytest.approx(0.5)
    # logloss of a confident-correct pair is small, wrong pair large
    ll = float(logloss(y, m, mask))
    assert 0.5 < ll < 1.5


def test_auc_weighted_mann_whitney(rng):
    """row_mask carries fractional example weights (feed.py); the AUC must
    be the weighted Mann-Whitney statistic, exact for non-binary weights."""
    n = 64
    y = (rng.random(n) < 0.4).astype(np.float32)
    m = rng.normal(size=n).astype(np.float32)
    w = rng.random(n).astype(np.float32) + 0.1
    # brute-force weighted AUC: sum over (pos, neg) pairs of wp*wn*[mp > mn]
    num = den = 0.0
    for i in range(n):
        for j in range(n):
            if y[i] > 0.5 and y[j] <= 0.5:
                den += w[i] * w[j]
                if m[i] > m[j]:
                    num += w[i] * w[j]
    expect = num / den
    got = float(auc(jnp.asarray(y), jnp.asarray(m), jnp.asarray(w)))
    assert got == pytest.approx(expect, abs=1e-5)
    # host pooled version agrees
    from wormhole_tpu.ops.metrics import auc_np
    assert auc_np(y, m, w) == pytest.approx(expect, abs=1e-6)


def test_hinge_loss_gradient():
    """hinge: objv = Σ max(0, 1-y·m); dual = -y on violated margins."""
    from wormhole_tpu.ops.loss import create_loss
    objv_fn, dual_fn = create_loss("hinge")
    m = jnp.asarray([0.5, 2.0, -0.5, -2.0])
    y = jnp.asarray([1.0, 1.0, 0.0, 0.0])
    mask = jnp.ones(4)
    # y=+1: margins .5 (viol, loss .5), 2.0 (ok); y=-1: -0.5 (viol, .5), -2 ok
    assert float(objv_fn(m, y, mask)) == pytest.approx(1.0)
    np.testing.assert_allclose(np.asarray(dual_fn(m, y, mask)),
                               [-1.0, 0.0, 1.0, 0.0])


def test_margin_hist_exact_counts():
    """The one-hot-matmul histogram (margin_hist replaced a serialized
    scatter-add; docs/perf.md) must produce EXACT counts: 0/1 weights are
    bf16-exact and the products accumulate in f32, so every bin equals
    the numpy histogram below 2^24 rows. Clipping maps out-of-range
    margins to the edge bins; masked rows contribute nothing."""
    from wormhole_tpu.ops.metrics import margin_hist
    rng = np.random.default_rng(0)
    n, bins, lo, hi = 50_000, 512, -8.0, 8.0
    margin = rng.normal(0, 6, n).astype(np.float32)   # some clip past +-8
    labels = (rng.random(n) < 0.3).astype(np.float32)
    mask = (rng.random(n) < 0.9).astype(np.float32)
    pos, neg = margin_hist(jnp.asarray(labels), jnp.asarray(margin),
                           jnp.asarray(mask), bins=bins, lo=lo, hi=hi)
    b = (np.clip((margin - lo) / (hi - lo), 0.0, 1.0)
         * (bins - 1)).astype(np.int64)
    want_pos = np.zeros(bins)
    want_neg = np.zeros(bins)
    np.add.at(want_pos, b, (labels > 0.5) * mask)
    np.add.at(want_neg, b, (labels <= 0.5) * mask)
    np.testing.assert_array_equal(np.asarray(pos), want_pos)
    np.testing.assert_array_equal(np.asarray(neg), want_neg)
    assert float(pos.sum() + neg.sum()) == float(mask.sum())

"""Kernel floor attribution — where do the fwd-kernel milliseconds go?

Round-4 established the tile kernels are NOT MXU-shape-bound (deleting a
whole matmul was time-neutral under separate timing). This harness makes
the diagnosis quantitative: an incremental-deletion series over the fwd
kernel, every variant timed INTERLEAVED in the same windows (the shared
chip's bursty contention hits all variants equally; min-of-windows per
variant), so per-stage deltas are trustworthy:

  F0 full            the production kernel body
  F1 -hist           per-subblock histogram matmuls (+their rhiT builds)
  F2 -rlo-mask       the row-lo spread select
  F3 -pick           the ones-matmul lane pick
  F4 -lo-mask        the bucket-lo select
  F5 -gather         the OH(hi) @ W matmul
  F6 builds-only     ohhi build + accumulate (the irreducible floor probe)
  I8 i8-gather       ohhi as int8 with an i8xi8 MXU dot on a quantized W
                     (VERDICT r4's untried lever — timing only; the i8
                     product is NOT numerically usable for f32 models)
  HO hoisted-builds  one-hot builds hoisted out of the tile loop (probes
                     whether builds serialize with the matmuls or overlap)

If stage deltas add up to ~F0, the units serialize and the floor model is
sum-of-stages; if F0 << sum, Mosaic overlaps and the floor is max().

Usage: python scripts/kfloor.py [reps] [windows]
"""
from __future__ import annotations

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

from wormhole_tpu.ops import tilemm  # noqa: E402
from wormhole_tpu.ops.tilemm import (A_HI, B_LO, HI_M, HI_SH, LO_M, LO_SH,  # noqa: E402
                                     RH, RHI_M, RHI_SH, RL, RLO_M, RLO_SH,
                                     TileSpec, _mask_sel, _oh_rep, _ohT_vec)

NB = 1 << 22
ROWS = 98304
NNZ = 39


def _lanepack_kernel(spec: TileSpec, only: bool, pw_ref, x_ref, w_ref,
                     mg_ref):
    """The full fwd chain with the pair-word RELAYOUT replaced by a
    static single-lane slice of a lane-packed pairs array x_ref
    (SG, N, TB): each tile's words sit in one LANE, so getting them onto
    sublanes is a native lane-broadcast (within-vreg) instead of the
    cross-vreg lanes->sublanes relayout that dominates the kernel.
    ``only`` mirrors the onlyrelay probe (slice+accumulate, no chain)."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        mg_ref[:] = jnp.zeros_like(mg_ref)

    S, GS, C, N = spec.subblocks, spec.group, spec.cap, spec.n
    TB = spec.tiles_step
    ones_pick = jnp.ones((B_LO, RL), jnp.bfloat16)
    for g in range(S // GS):
        mgs = [mg_ref[g * GS + j] for j in range(GS)]
        xg = x_ref[0, g].astype(jnp.int32)       # (N, TB) words on lanes
        for tb in range(TB):
            rep = xg[:, tb:tb + 1]               # lane slice, no relayout
            if only:
                for j in range(GS):
                    mgs[j] += (rep[j * C:j * C + RH]
                               .astype(jnp.float32)
                               * jnp.ones((RH, RL), jnp.float32))
                continue
            wt = w_ref[tb]
            pc = pw_ref[tb, g].astype(jnp.int32)
            ohhi = _oh_rep(rep, HI_SH, HI_M, N, 128)
            m = jnp.dot(ohhi, wt, preferred_element_type=jnp.float32)
            wp = jnp.dot(_mask_sel(rep, LO_SH, LO_M, m), ones_pick,
                         preferred_element_type=jnp.float32)
            rhs = _mask_sel(rep, RLO_SH, RLO_M, wp)
            for j in range(GS):
                rhiT = _ohT_vec(pc[j * C:(j + 1) * C], RHI_SH, RHI_M,
                                RH, C)
                mgs[j] += jnp.dot(rhiT, rhs[j * C:(j + 1) * C],
                                  preferred_element_type=jnp.float32)
        for j in range(GS):
            mg_ref[g * GS + j] = mgs[j]


def build_lanepack(spec: TileSpec, only: bool):
    T, TB = spec.tiles, spec.tiles_step
    SG, N, S = spec.subblocks // spec.group, spec.n, spec.subblocks

    @jax.jit
    def fwd(pw, x, w):
        wt = w.reshape(T, A_HI, B_LO).astype(jnp.bfloat16)
        return pl.pallas_call(
            partial(_lanepack_kernel, spec, only),
            grid=(T // TB,),
            in_specs=[
                pl.BlockSpec((TB, SG, N), lambda t: (t, 0, 0)),
                pl.BlockSpec((1, SG, N, TB), lambda t: (t, 0, 0, 0)),
                pl.BlockSpec((TB, A_HI, B_LO), lambda t: (t, 0, 0)),
            ],
            out_specs=pl.BlockSpec((S, RH, RL), lambda t: (0, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((S, RH, RL), jnp.float32),
            compiler_params=pltpu.CompilerParams(
                vmem_limit_bytes=100 * 1024 * 1024),
        )(pw, x, wt)

    return fwd


def _variant_kernel(spec: TileSpec, stage: str, pw_ref, w_ref, mg_ref):
    """The fwd kernel with later stages progressively deleted.

    stage one of: full, nohist, norlo, nopick, nolo, nogather, builds,
    i8, hoist."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        mg_ref[:] = jnp.zeros_like(mg_ref)

    S, GS, C, N = spec.subblocks, spec.group, spec.cap, spec.n
    TB = spec.tiles_step
    ones_pick = jnp.ones((B_LO, RL), jnp.bfloat16)
    for g in range(S // GS):
        mgs = [mg_ref[g * GS + j] for j in range(GS)]
        if stage == "onlyrelay":
            # the relayout alone: one (N,1) lanes->sublanes per (g,tb),
            # consumed by a trivial accumulate
            for tb in range(TB):
                rep = pw_ref[tb, g].astype(jnp.int32)[:, None]
                for j in range(GS):
                    mgs[j] += (rep[j * C:j * C + RH]
                               .astype(jnp.float32) * jnp.ones(
                                   (RH, RL), jnp.float32))
            for j in range(GS):
                mg_ref[g * GS + j] = mgs[j]
            continue
        if stage == "batchrelay":
            # ONE relayout per g covering every tile's pairs; the full
            # production chain otherwise — probes whether the relayout
            # cost is per-issue (latency) or per-element (throughput)
            pc_all = pw_ref[:, g].reshape(TB * N).astype(jnp.int32)
            rep_all = pc_all[:, None]
            for tb in range(TB):
                wt = w_ref[tb]
                pc = pw_ref[tb, g].astype(jnp.int32)
                rep = rep_all[tb * N:(tb + 1) * N]
                ohhi = _oh_rep(rep, HI_SH, HI_M, N, 128)
                m = jnp.dot(ohhi, wt, preferred_element_type=jnp.float32)
                wp = jnp.dot(_mask_sel(rep, LO_SH, LO_M, m), ones_pick,
                             preferred_element_type=jnp.float32)
                rhs = _mask_sel(rep, RLO_SH, RLO_M, wp)
                for j in range(GS):
                    rhiT = _ohT_vec(pc[j * C:(j + 1) * C], RHI_SH,
                                    RHI_M, RH, C)
                    mgs[j] += jnp.dot(rhiT, rhs[j * C:(j + 1) * C],
                                      preferred_element_type=jnp.float32)
            for j in range(GS):
                mg_ref[g * GS + j] = mgs[j]
            continue
        if stage == "norelay":
            # no relayout at all: a synthetic iota rep stands in (wrong
            # results, same op structure) — delta vs full == the whole
            # relayout bill
            for tb in range(TB):
                wt = w_ref[tb]
                pc = pw_ref[tb, g].astype(jnp.int32)
                rep = (jax.lax.broadcasted_iota(jnp.int32, (N, 1), 0)
                       * (tb + 1))
                ohhi = _oh_rep(rep, HI_SH, HI_M, N, 128)
                m = jnp.dot(ohhi, wt, preferred_element_type=jnp.float32)
                wp = jnp.dot(_mask_sel(rep, LO_SH, LO_M, m), ones_pick,
                             preferred_element_type=jnp.float32)
                rhs = _mask_sel(rep, RLO_SH, RLO_M, wp)
                for j in range(GS):
                    rhiT = _ohT_vec(pc[j * C:(j + 1) * C], RHI_SH,
                                    RHI_M, RH, C)
                    mgs[j] += jnp.dot(rhiT, rhs[j * C:(j + 1) * C],
                                      preferred_element_type=jnp.float32)
            for j in range(GS):
                mg_ref[g * GS + j] = mgs[j]
            continue
        if stage == "hoist":
            # builds for tb=0 reused across the tile loop: same matmul
            # count, 1/tiles_step the VPU build work
            pc0 = pw_ref[0, g].astype(jnp.int32)
            rep0 = pc0[:, None]
            ohhi0 = _oh_rep(rep0, HI_SH, HI_M, N, 128)
            rhiTs0 = [_ohT_vec(pc0[j * C:(j + 1) * C], RHI_SH, RHI_M,
                               RH, C) for j in range(GS)]
        for tb in range(spec.tiles_step):
            if stage == "hoist":
                wt = w_ref[tb]
                m = jnp.dot(ohhi0, wt, preferred_element_type=jnp.float32)
                wp = jnp.dot(_mask_sel(rep0, LO_SH, LO_M, m), ones_pick,
                             preferred_element_type=jnp.float32)
                rhs = _mask_sel(rep0, RLO_SH, RLO_M, wp)
                for j in range(GS):
                    mgs[j] += jnp.dot(rhiTs0[j], rhs[j * C:(j + 1) * C],
                                      preferred_element_type=jnp.float32)
                continue
            wt = w_ref[tb]
            pc = pw_ref[tb, g].astype(jnp.int32)
            rep = pc[:, None]
            if stage == "i8":
                iota = jax.lax.broadcasted_iota(jnp.int32, (N, 128), 1)
                ohhi8 = (((rep >> HI_SH) & HI_M) == iota).astype(jnp.int8)
                w8 = wt.astype(jnp.int8)      # timing stand-in quantize
                m = jax.lax.dot_general(
                    ohhi8, w8, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32).astype(jnp.float32)
            else:
                ohhi = _oh_rep(rep, HI_SH, HI_M, N, 128)
                if stage == "builds":
                    for j in range(GS):
                        mgs[j] += ohhi[j * C:j * C + RH, :RL].astype(
                            jnp.float32)
                    continue
                if stage == "nogather":
                    m = (rep & 0x7FFFFF).astype(jnp.float32) * ohhi.astype(
                        jnp.float32)[:, :128]
                else:
                    m = jnp.dot(ohhi, wt,
                                preferred_element_type=jnp.float32)
            if stage == "nolo" or stage == "nogather":
                wp_in = m.astype(jnp.bfloat16)
            else:
                wp_in = _mask_sel(rep, LO_SH, LO_M, m)
            if stage == "nopick":
                wp = m
            else:
                wp = jnp.dot(wp_in, ones_pick,
                             preferred_element_type=jnp.float32)
            if stage == "norlo" or stage == "nopick":
                rhs = wp.astype(jnp.bfloat16)
            else:
                rhs = _mask_sel(rep, RLO_SH, RLO_M, wp)
            if stage == "nohist":
                for j in range(GS):
                    mgs[j] += rhs[j * C:j * C + RH, :RL].astype(jnp.float32)
            else:
                rhiTs = [_ohT_vec(pc[j * C:(j + 1) * C], RHI_SH, RHI_M,
                                  RH, C) for j in range(GS)]
                for j in range(GS):
                    mgs[j] += jnp.dot(rhiTs[j], rhs[j * C:(j + 1) * C],
                                      preferred_element_type=jnp.float32)
        for j in range(GS):
            mg_ref[g * GS + j] = mgs[j]


def build_variant(spec: TileSpec, stage: str):
    T, TB = spec.tiles, spec.tiles_step
    SG, N, S = spec.subblocks // spec.group, spec.n, spec.subblocks

    @jax.jit
    def fwd(pw, w):
        wt = w.reshape(T, A_HI, B_LO).astype(jnp.bfloat16)
        return pl.pallas_call(
            partial(_variant_kernel, spec, stage),
            grid=(T // TB,),
            in_specs=[
                pl.BlockSpec((TB, SG, N), lambda t: (t, 0, 0)),
                pl.BlockSpec((TB, A_HI, B_LO), lambda t: (t, 0, 0)),
            ],
            out_specs=pl.BlockSpec((S, RH, RL), lambda t: (0, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((S, RH, RL), jnp.float32),
            compiler_params=pltpu.CompilerParams(
                vmem_limit_bytes=100 * 1024 * 1024),
        )(pw, wt)

    return fwd


def _force(o):
    float(np.asarray(o.ravel()[0]))


def main():
    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 15
    windows = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    from wormhole_tpu.data.crec import default_cap
    spec = tilemm.make_spec(NB, ROWS // tilemm.RSUB, default_cap(NNZ, NB))
    print("spec:", spec, flush=True)
    rng = np.random.default_rng(0)
    buckets = rng.integers(0, NB, size=ROWS * NNZ, dtype=np.int64)
    rows = np.repeat(np.arange(ROWS, dtype=np.int64), NNZ)
    pw_np, _, _ = tilemm.encode_block(buckets, rows, spec)
    w_np = rng.normal(0, 0.1, NB).astype(np.float32)
    pw, w = jax.device_put(pw_np), jax.device_put(w_np)

    # lane-packed pairs: (T, SG, N) -> (T//TB, SG, N, TB), words of the
    # 16 tiles of one grid step side by side on lanes
    TB = spec.tiles_step
    x_np = (pw_np.reshape(spec.tiles // TB, TB, pw_np.shape[1],
                          pw_np.shape[2])
            .transpose(0, 2, 3, 1).copy())
    x = jax.device_put(x_np)

    stages = ["full", "nohist", "norlo", "nopick", "nolo", "nogather",
              "builds", "i8", "hoist", "onlyrelay", "norelay",
              "lanepack", "lanepackonly"]
    fns = {}
    for s in stages:
        t0 = time.perf_counter()
        try:
            if s.startswith("lanepack"):
                raw = build_lanepack(spec, s == "lanepackonly")
                fn = (lambda pw_, w_, _r=raw: _r(pw_, x, w_))
            else:
                fn = build_variant(spec, s)
            _force(fn(pw, w))          # compile
            fns[s] = fn
            print(f"  compiled {s:12s} in {time.perf_counter()-t0:6.1f}s",
                  flush=True)
        except Exception as e:  # noqa: BLE001 — i8 may be rejected
            print(f"  {s}: COMPILE FAILED: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)
    if "lanepack" in fns and "full" in fns:
        d = np.max(np.abs(np.asarray(fns["lanepack"](pw, w))
                          - np.asarray(fns["full"](pw, w))))
        print(f"  lanepack vs full: max|diff| = {d:.3e}", flush=True)
    # burn-in past the post-compile ramp
    for _ in range(60):
        o = fns["full"](pw, w)
    _force(o)
    best = {s: float("inf") for s in fns}
    for _ in range(windows):
        for s in fns:                  # interleaved: same contention
            t0 = time.perf_counter()
            for _ in range(reps):
                o = fns[s](pw, w)
            _force(o)
            best[s] = min(best[s], (time.perf_counter() - t0) / reps)
    full = best.get("full", float("nan"))
    print(f"\n{'stage':10s} {'ms':>8s} {'delta vs full':>14s}")
    for s in stages:
        if s in best:
            print(f"{s:10s} {best[s]*1e3:8.3f} "
                  f"{(full-best[s])*1e3:+13.3f}")
    # additivity check: do the stage deltas reconstruct the total?
    chain = ["nohist", "norlo", "nopick", "nolo", "nogather"]
    if all(s in best for s in chain):
        deltas = []
        prev = full
        for s in chain:
            deltas.append(prev - best[s])
            prev = best[s]
        print("\nstage costs (serialized-model attribution):")
        for s, d in zip(["hist", "rlo-mask", "pick", "lo-mask", "gather"],
                        deltas):
            print(f"  {s:10s} {d*1e3:8.3f} ms")
        print(f"  residual (builds+grid): {best['nogather']*1e3:.3f} ms")


if __name__ == "__main__":
    main()

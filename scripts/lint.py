#!/usr/bin/env python
"""Run the whole static-analysis suite in one process, one parse/file.

All nine checkers (six migrated legacy lints + WH-DONATE, WH-THREAD,
WH-HOSTSYNC) share a single engine pass over ``wormhole_tpu/``: one
file read, one comment-strip and at most one AST parse per file,
instead of six separate script invocations each rewalking the tree.

Usage::

    python scripts/lint.py                 # run everything
    python scripts/lint.py --list          # show the checker catalog
    python scripts/lint.py --only spans,donation
    python scripts/lint.py --json          # machine-readable findings

Exit codes: 0 all green, 1 findings, 2 tree layout missing (no
wormhole_tpu/ package under --root).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from wormhole_tpu.analysis import Engine                   # noqa: E402
from wormhole_tpu.analysis.checkers import (ALL_CHECKERS,  # noqa: E402
                                            BY_NAME)


def run(root: str, only=None, as_json=False) -> int:
    names = list(only) if only else [c.name for c in ALL_CHECKERS]
    unknown = [n for n in names if n not in BY_NAME]
    if unknown:
        print(f"lint: unknown checker(s): {', '.join(unknown)} "
              f"(see --list)", file=sys.stderr)
        return 2
    checkers = [BY_NAME[n](root) for n in names]
    ready = []
    rc = 0
    for chk in checkers:
        err = chk.precheck()
        if err is None:
            ready.append(chk)
        else:
            print(err, file=sys.stderr)
            rc = 2
    if rc:
        return rc
    eng = Engine(root, ready)
    diags = eng.run()
    if as_json:
        payload = {
            "root": os.path.abspath(root),
            "files": eng.files_scanned,
            "parses": eng.parses,
            "checkers": [
                {"name": chk.name, "code": chk.code,
                 "ok": not chk.diagnostics,
                 "findings": [{"rel": d.rel, "line": d.line,
                               "message": d.message}
                              for d in chk.diagnostics],
                 "warnings": list(chk.warnings)}
                for chk in ready],
        }
        print(json.dumps(payload, indent=2, sort_keys=True))
        return 1 if diags else 0
    for chk in ready:
        for w in chk.warnings:
            print(w, file=sys.stderr)
    if diags:
        for d in diags:
            print(d.format(), file=sys.stderr)
        bad = sorted({chk.name for chk in ready if chk.diagnostics})
        print(f"lint: FAIL ({len(diags)} finding"
              f"{'s' if len(diags) != 1 else ''} from "
              f"{', '.join(bad)})", file=sys.stderr)
        return 1
    for chk in ready:
        print(chk.ok_line())
    print(f"lint: OK ({len(ready)} checkers, {eng.files_scanned} "
          f"files, {eng.parses} parses)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=".",
                    help="repo root containing wormhole_tpu/ "
                         "(default: cwd)")
    ap.add_argument("--list", action="store_true",
                    help="list the checker catalog and exit")
    ap.add_argument("--only", default=None,
                    help="comma-separated checker names to run")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON on stdout")
    args = ap.parse_args(argv)
    if args.list:
        for cls in ALL_CHECKERS:
            mod = sys.modules[cls.__module__]
            doc = (mod.__doc__ or "").strip().splitlines()
            head = doc[0] if doc else ""
            print(f"{cls.name:<12} {cls.code:<14} {head}")
        return 0
    only = ([n.strip() for n in args.only.split(",") if n.strip()]
            if args.only else None)
    return run(args.root, only=only, as_json=args.as_json)


if __name__ == "__main__":
    sys.exit(main())

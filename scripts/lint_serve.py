#!/usr/bin/env python
"""Lint: nothing under wormhole_tpu/serve/ may touch a training entry point.

The serving tier's one invariant is that it is PULL-ONLY (the reference
worker's ZPull without the ZPush half): it reads model snapshots and
computes margins; it never updates parameters, never touches optimizer
state, never scatters into a table. The invariant is what makes the
hot-swap sound — a serve-side write would race the training loop and
tear the "one consistent model per batch" guarantee the swap provides.

This lint enforces it statically: every Python file under
``wormhole_tpu/serve/`` is scanned (comments stripped) for the training
store's mutation surface — push/update/optimizer entry points and raw
scatter-adds. A serving feature that needs any of these is not a
serving feature; it belongs in learners/ behind the store API.

Run from the repo root (or pass ``--root``)::

    python scripts/lint_serve.py
"""

from __future__ import annotations

import argparse
import os
import re
import sys

# The training mutation surface, as call-site patterns. Textual on
# purpose (same rationale as lint_scatters): it must catch the names
# inside strings being exec'd or built dynamically too, and a false
# positive in serve/ code is itself a smell worth renaming away.
FORBIDDEN = [
    # fused/tile/dense training steps (store.train_step, tile_train_step,
    # _dense_step train kind is reached only through train_step)
    (re.compile(r"\btrain_step\b"), "training step dispatch"),
    # delay-tolerant split pipeline (DT2 pull computes gradients and its
    # push applies them; BOTH are training-only)
    (re.compile(r"\bdt2_push\b"), "DT2 delayed push"),
    (re.compile(r"\bdt2_pull\b"), "DT2 gradient pull (training half)"),
    # handle/optimizer update entry points
    (re.compile(r"\.push\s*\("), "parameter push (optimizer update)"),
    (re.compile(r"\bmasked_push\b"), "masked parameter push"),
    (re.compile(r"\bbackward_grad\b"), "gradient computation for push"),
    (re.compile(r"\bbackward_pushes\b"), "tile backward push pipeline"),
    # raw scatter-add into a table (the push primitive itself)
    (re.compile(r"\.at\s*\[[^\]]*\]\s*\.add\s*\(", re.S),
     "scatter-add into a parameter table"),
    # restoring state INTO the training store from serve code would be a
    # write to the trainer's model; serve loads into its own standby
    (re.compile(r"\brestore_pytree\b"), "training-store state restore"),
]


def _strip_comments(text: str) -> str:
    """Drop `#`-to-EOL per line (keeps line numbers aligned)."""
    return "\n".join(ln.split("#", 1)[0] for ln in text.splitlines())


def scan_file(path: str) -> list:
    """Return ``(line, reason)`` violations in ``path``."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        text = _strip_comments(f.read())
    out = []
    for pat, reason in FORBIDDEN:
        out.extend((text.count("\n", 0, m.start()) + 1, reason)
                   for m in pat.finditer(text))
    return sorted(out)


def run(root: str) -> int:
    """Scan ``root``/wormhole_tpu/serve for violations; return an rc."""
    pkg = os.path.join(root, "wormhole_tpu", "serve")
    if not os.path.isdir(pkg):
        print(f"lint_serve: no wormhole_tpu/serve package under {root!r}",
              file=sys.stderr)
        return 2
    violations = []
    nfiles = 0
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            nfiles += 1
            violations.extend(f"{rel}:{ln}: {reason}"
                              for ln, reason in scan_file(path))
    if violations:
        print("lint_serve: serving code reaching a training mutation "
              "entry point (serve/ is pull-only):", file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        print("serving must never push/update/scatter — if the feature "
              "needs writes, it belongs in learners/ behind the store "
              "API, not under wormhole_tpu/serve/", file=sys.stderr)
        return 1
    print(f"lint_serve: OK ({nfiles} serve files pull-only)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=".",
                    help="repo root containing wormhole_tpu/serve/ "
                         "(default: cwd)")
    args = ap.parse_args(argv)
    return run(args.root)


if __name__ == "__main__":
    sys.exit(main())

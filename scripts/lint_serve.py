#!/usr/bin/env python
"""Lint: nothing under wormhole_tpu/serve/ may touch a training entry point.

Thin shim: the checker now lives on the shared analysis engine as
``wormhole_tpu.analysis.checkers.serve`` (WH-SERVE) and also runs via
``scripts/lint.py``. This script re-exports the legacy module API
(``FORBIDDEN``, ``scan_file``, ``run``) and keeps the legacy CLI and
output.

Run from the repo root (or pass ``--root``)::

    python scripts/lint_serve.py
"""

from __future__ import annotations

import argparse
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from wormhole_tpu.analysis.checkers.serve import (  # noqa: E402,F401
    FORBIDDEN,
    ServeChecker,
    _strip_comments,
    run,
    scan_file,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=".",
                    help="repo root containing wormhole_tpu/serve/ "
                         "(default: cwd)")
    args = ap.parse_args(argv)
    return run(args.root)


if __name__ == "__main__":
    sys.exit(main())

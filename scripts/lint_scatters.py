#!/usr/bin/env python
"""Lint: no serialized scatter-adds (``.at[...].add``) outside the allowlist.

XLA:TPU lowers ``x.at[idx].add(v)`` to a serialized per-element update
loop (~13-25ns/element), which is exactly the pathology ops/tilemm.py and
ops/histmm.py exist to avoid: both reformulate the scatter as a one-hot
matmul on the MXU. This lint keeps the win from regressing — a new
``.at[...].add`` in an unaudited file fails the build until it is either
rewritten as a matmul or consciously added below with a reason.

The check is textual (comments stripped, bracket contents may span
lines), not an AST walk: it must catch the pattern inside strings being
exec'd or built up for pallas too, and false positives are resolved by
the allowlist anyway.

Run from the repo root (or pass ``--root``)::

    python scripts/lint_scatters.py
"""

from __future__ import annotations

import argparse
import os
import re
import sys

# Audited files that legitimately keep `.at[...].add` sites. Every entry
# carries the reason the scatter is acceptable there. models/gbdt.py is
# deliberately ABSENT: its level-histogram scatters moved to ops/histmm
# (PR 2) and must not come back.
ALLOWLIST = {
    "wormhole_tpu/ops/spmv.py":
        "documented scatter fallback for the y = A^T x product; the "
        "matmul path is the default, this is the oracle",
    "wormhole_tpu/ops/tilemm.py":
        "COO overflow-bucket spill: O(overflow) elements, not O(nnz); "
        "the hot tile path is already a one-hot matmul",
    "wormhole_tpu/ops/histmm.py":
        "the scatter ORACLE kernels (_dense_scatter/_sparse_scatter) "
        "that the matmul kernels are parity-tested against",
    "wormhole_tpu/solver/lbfgs.py":
        "two-loop recursion history update: O(lbfgs_memory) ~ 10 "
        "elements, nothing to vectorize",
    "wormhole_tpu/models/kmeans.py":
        "per-cluster count/weight stats: O(clusters) cells, dominated "
        "by the distance matmul",
}

# Files whose scatters are live RUNTIME fallbacks — the paths the online
# tile encoder (data/crec.TileOnlineFeed) and the `tile_online=auto`
# admission gate route real traffic through when the tile path is
# inadmissible. A blanket allowlist would let new, unrelated scatters
# hide in these hot files, so instead EVERY `.at[...].add` site here must
# carry a `scatter-fallback:` comment (same line or the two lines above)
# saying why that particular scatter stays.
ANNOTATED = {
    "wormhole_tpu/learners/store.py":
        "uniq-key push, v1 dense-apply grad, overflow spills",
    "wormhole_tpu/models/fm.py":
        "uniq-key push + tile overflow spill",
    "wormhole_tpu/models/wide_deep.py":
        "uniq-key push + tile overflow spill",
}

# the in-source audit marker required at each scatter site in ANNOTATED
# files (comment text, so it survives _strip_comments only in raw form)
MARKER = "scatter-fallback:"

# `.at[` ... `].add(` with the subscript allowed to span lines; targets
# only scatter-ADD — `.at[].set/.max/.min/.mul` have different lowering
# and are not what tilemm/histmm replace.
_PAT = re.compile(r"\.at\s*\[[^\]]*\]\s*\.add\s*\(", re.S)


def _strip_comments(text: str) -> str:
    """Drop `#`-to-EOL per line (keeps line numbers aligned). Naive about
    `#` inside string literals — good enough for a lint whose false
    positives land in a human-reviewed allowlist."""
    return "\n".join(ln.split("#", 1)[0] for ln in text.splitlines())


def scan_file(path: str) -> list:
    """Return 1-based line numbers of scatter-add sites in ``path``."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        text = _strip_comments(f.read())
    return [text.count("\n", 0, m.start()) + 1
            for m in _PAT.finditer(text)]


def unannotated_sites(path: str, lines: list) -> list:
    """Scatter sites (1-based line numbers) lacking the ``MARKER``
    comment on the same line or within the two preceding lines."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        raw = f.read().splitlines()
    out = []
    for ln in lines:
        window = raw[max(ln - 3, 0):ln]
        if not any(MARKER in w for w in window):
            out.append(ln)
    return out


def run(root: str) -> int:
    """Scan ``root``/wormhole_tpu for violations; return a process rc."""
    pkg = os.path.join(root, "wormhole_tpu")
    if not os.path.isdir(pkg):
        print(f"lint_scatters: no wormhole_tpu package under {root!r}",
              file=sys.stderr)
        return 2
    violations = []
    unannotated = []
    seen_allowed = set()
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            lines = scan_file(path)
            if not lines:
                continue
            if rel in ANNOTATED:
                seen_allowed.add(rel)
                unannotated.extend(
                    f"{rel}:{ln}"
                    for ln in unannotated_sites(path, lines))
            elif rel in ALLOWLIST:
                seen_allowed.add(rel)
            else:
                violations.extend(f"{rel}:{ln}" for ln in lines)
    for rel in sorted((set(ALLOWLIST) | set(ANNOTATED)) - seen_allowed):
        # stale entries are a warning, not a failure: deleting the last
        # scatter from an audited file should not break the build
        print(f"lint_scatters: allowlist entry {rel} has no "
              f"scatter-adds (stale?)", file=sys.stderr)
    if violations:
        print("lint_scatters: serialized scatter-add (`.at[...].add`) "
              "outside the allowlist:", file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        print("either reformulate as a one-hot matmul (see ops/histmm.py"
              " / ops/tilemm.py) or add the file to ALLOWLIST in "
              "scripts/lint_scatters.py with a reason", file=sys.stderr)
    if unannotated:
        print("lint_scatters: runtime-fallback scatter without a "
              f"`{MARKER}` audit comment (same line or the two lines "
              "above):", file=sys.stderr)
        for v in unannotated:
            print(f"  {v}", file=sys.stderr)
        print("these files carry live scatter fallbacks (the online "
              "tile-encode overflow route); each site must say why it "
              "stays a scatter", file=sys.stderr)
    if violations or unannotated:
        return 1
    print(f"lint_scatters: OK ({len(seen_allowed)} audited files, "
          f"{len(ANNOTATED)} annotated)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=".",
                    help="repo root containing wormhole_tpu/ "
                         "(default: cwd)")
    args = ap.parse_args(argv)
    return run(args.root)


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Lint: no serialized scatter-adds (``.at[...].add``) outside the allowlist.

Thin shim: the checker now lives on the shared analysis engine as
``wormhole_tpu.analysis.checkers.scatters`` (WH-SCATTER) and also runs
via ``scripts/lint.py``. This script re-exports the legacy module API
(tables, ``scan_file``, ``unannotated_sites``, ``run``) and keeps the
legacy CLI and output so existing tests and muscle memory keep
working.

Run from the repo root (or pass ``--root``)::

    python scripts/lint_scatters.py
"""

from __future__ import annotations

import argparse
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from wormhole_tpu.analysis.checkers.scatters import (  # noqa: E402,F401
    ALLOWLIST,
    ANNOTATED,
    MARKER,
    ScatterChecker,
    _PAT,
    _strip_comments,
    run,
    scan_file,
    unannotated_sites,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=".",
                    help="repo root containing wormhole_tpu/ "
                         "(default: cwd)")
    args = ap.parse_args(argv)
    return run(args.root)


if __name__ == "__main__":
    sys.exit(main())

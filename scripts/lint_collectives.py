#!/usr/bin/env python
"""Lint: no direct ``multihost_utils`` use outside wormhole_tpu/parallel/,
and every learners/ collective call site audited for engine routing.

Rule 1 — every host-level DCN hop must go through
parallel/collectives.py (``allreduce_tree`` / ``allgather_tree`` /
``broadcast_tree`` / ``host_local_to_global``): that is where the
ps-lite filter chain (parallel/filters.py — KEY_CACHING / FIXING_FLOAT
/ COMPRESSING) and the wire-byte accounting (``comm/bytes_raw`` etc.)
live. A call site that imports ``jax.experimental.multihost_utils``
directly bypasses both — its payload ships unfiltered and its bytes
vanish from the comm counters — so this lint fails the build until the
site is rewritten against the wrappers or consciously allowlisted with
a reason.

Rule 2 — with the bounded-staleness engine (wormhole_tpu/ps/) live, a
training pass may only issue host collectives from the engine's single
drain thread: a second thread issuing its own collective can interleave
differently across ranks and deadlock the mesh. Every
``allreduce_tree`` / ``allgather_tree`` / ``broadcast_tree`` call site
under ``wormhole_tpu/learners/`` must therefore carry an audit marker
within the preceding few lines: ``# ps-engine:`` (the call routes
through ``ExchangeEngine.submit/exchange`` — e.g. via ``_ctl``) or
``# bsp-direct:`` (the call provably never coexists with a live
engine, e.g. the crec BSP pass the engine dispatch excludes). An
unmarked site means nobody decided, which is how the deadlock ships.

The checks are textual (rule 1 strips comments; rule 2 reads them),
not an AST walk: they must catch lazy function-level imports and
closures built inside call arguments, and false positives are resolved
by the allowlist / a marker anyway.

Run from the repo root (or pass ``--root``)::

    python scripts/lint_collectives.py
"""

from __future__ import annotations

import argparse
import os
import re
import sys

# Audited files outside parallel/ that legitimately reference
# multihost_utils. Every entry carries the reason. Deliberately EMPTY:
# the PR that introduced this lint rewrote every call site against the
# parallel/ wrappers, and new entries should be rare and argued.
ALLOWLIST: dict = {}

_PAT = re.compile(r"\bmultihost_utils\b")

# rule 2: learners/ collective call sites and their audit markers
_CALL_PAT = re.compile(
    r"\b(allreduce_tree|allgather_tree|broadcast_tree)\s*\(")
_MARKER_PAT = re.compile(r"#\s*(ps-engine|bsp-direct):")
_MARKER_WINDOW = 3   # marker may sit up to this many lines above the call


def _strip_comments(text: str) -> str:
    """Drop `#`-to-EOL per line (keeps line numbers aligned). Naive about
    `#` inside string literals — good enough for a lint whose false
    positives land in a human-reviewed allowlist."""
    return "\n".join(ln.split("#", 1)[0] for ln in text.splitlines())


def scan_file(path: str) -> list:
    """Return 1-based line numbers of multihost_utils references."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        text = _strip_comments(f.read())
    return [text.count("\n", 0, m.start()) + 1
            for m in _PAT.finditer(text)]


def scan_markers(path: str) -> list:
    """Rule 2: return ``(line, callee)`` for every collective call site
    without a ``# ps-engine:`` / ``# bsp-direct:`` audit marker on the
    call line or the :data:`_MARKER_WINDOW` lines above it."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        raw = f.read()
    raw_lines = raw.splitlines()
    code_lines = _strip_comments(raw).splitlines()
    out = []
    for i, ln in enumerate(code_lines):
        m = _CALL_PAT.search(ln)
        if m is None:
            continue
        lo = max(0, i - _MARKER_WINDOW)
        if any(_MARKER_PAT.search(r) for r in raw_lines[lo:i + 1]):
            continue
        out.append((i + 1, m.group(1)))
    return out


def run(root: str) -> int:
    """Scan ``root``/wormhole_tpu for violations; return a process rc."""
    pkg = os.path.join(root, "wormhole_tpu")
    if not os.path.isdir(pkg):
        print(f"lint_collectives: no wormhole_tpu package under {root!r}",
              file=sys.stderr)
        return 2
    violations = []
    unmarked = []
    seen_allowed = set()
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if rel.startswith("wormhole_tpu/parallel/"):
                continue  # parallel/ owns the raw transport
            if rel.startswith("wormhole_tpu/learners/"):
                unmarked.extend(f"{rel}:{ln} ({name})"
                                for ln, name in scan_markers(path))
            lines = scan_file(path)
            if not lines:
                continue
            if rel in ALLOWLIST:
                seen_allowed.add(rel)
            else:
                violations.extend(f"{rel}:{ln}" for ln in lines)
    for rel in sorted(set(ALLOWLIST) - seen_allowed):
        # stale entries are a warning, not a failure: deleting the last
        # reference from an audited file should not break the build
        print(f"lint_collectives: allowlist entry {rel} has no "
              f"multihost_utils references (stale?)", file=sys.stderr)
    if violations:
        print("lint_collectives: direct multihost_utils use outside "
              "wormhole_tpu/parallel/:", file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        print("route the call through parallel/collectives.py "
              "(allreduce_tree / allgather_tree / broadcast_tree / "
              "host_local_to_global) so it rides the filter chain and "
              "the comm byte counters, or add the file to ALLOWLIST in "
              "scripts/lint_collectives.py with a reason",
              file=sys.stderr)
        return 1
    if unmarked:
        print("lint_collectives: learners/ collective call sites without "
              "an engine-routing audit marker:", file=sys.stderr)
        for v in unmarked:
            print(f"  {v}", file=sys.stderr)
        print("mark the site `# ps-engine:` (it runs on the exchange "
              "engine's drain thread — ExchangeEngine.submit/exchange, "
              "e.g. via AsyncSGD._ctl) or `# bsp-direct:` (it provably "
              "never coexists with a live engine) within "
              f"{_MARKER_WINDOW} lines above the call",
              file=sys.stderr)
        return 1
    print(f"lint_collectives: OK ({len(seen_allowed)} allowlisted files)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=".",
                    help="repo root containing wormhole_tpu/ "
                         "(default: cwd)")
    args = ap.parse_args(argv)
    return run(args.root)


if __name__ == "__main__":
    sys.exit(main())

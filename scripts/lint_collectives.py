#!/usr/bin/env python
"""Lint: one transport layer, one marker form.

Thin shim: the checker now lives on the shared analysis engine as
``wormhole_tpu.analysis.checkers.collectives`` (WH-COLLECTIVE) and
also runs via ``scripts/lint.py``. This script re-exports the legacy
module API (``TRANSPORT_HOME``, ``ALLOWLIST``, ``scan_file``,
``scan_markers``, ``run``) and keeps the legacy CLI and output.

Run from the repo root (or pass ``--root``)::

    python scripts/lint_collectives.py
"""

from __future__ import annotations

import argparse
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from wormhole_tpu.analysis.checkers.collectives import (  # noqa: E402,F401
    ALLOWLIST,
    TRANSPORT_HOME,
    CollectiveChecker,
    _CALL_PAT,
    _MARKER_PAT,
    _MARKER_WINDOW,
    _OLD_MARKER_PAT,
    _PAT,
    _ROUTES,
    _strip_comments,
    run,
    scan_file,
    scan_markers,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=".",
                    help="repo root containing wormhole_tpu/ "
                         "(default: cwd)")
    args = ap.parse_args(argv)
    return run(args.root)


if __name__ == "__main__":
    sys.exit(main())

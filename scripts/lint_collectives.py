#!/usr/bin/env python
"""Lint: one transport layer, one marker form.

Rule 1 — raw collective transport lives in exactly ONE file:
``wormhole_tpu/parallel/transport.py`` (the ``ProcessWire``). Every
other file in the package — including the rest of ``parallel/`` — must
reach the wire through the transport stack (``parallel/collectives.py``
delegates to it). A site that imports ``jax.experimental``'s multihost
helpers directly bypasses the seq/span stamping, the watchdog guard,
the ps-lite filter chain (parallel/filters.py — KEY_CACHING /
FIXING_FLOAT / COMPRESSING) and the wire-byte accounting
(``comm/bytes_raw`` etc.) — its payload ships unfiltered and its bytes
vanish from the comm counters — so this lint fails the build until the
site is rewritten against the wrappers or consciously allowlisted with
a reason.

Rule 2 — every collective call site outside ``wormhole_tpu/parallel/``
(``allreduce_tree`` / ``allgather_tree`` / ``broadcast_tree``) must
carry a single-form routing marker within the preceding few lines::

    # transport: engine — <why this runs on the drain thread>
    # transport: direct — <why this never coexists with a live engine>
    # transport: mesh   — <in-jit psum leg; tree call is the fallback>

``engine`` means the call routes through ``ExchangeEngine.submit /
exchange`` (a second thread issuing its own collective can interleave
differently across ranks and deadlock the mesh — the engine's single
drain thread is the only thread allowed to block on the wire while a
training pass is live). ``direct`` means the call provably never
coexists with a live engine (BSP passes, startup/shutdown barriers,
metrics windows the engine quiesces around). ``mesh`` marks a site
whose hot path is the in-jit ICI psum and the tree call is a host-side
fallback or reduction of the psum result. An unmarked site means
nobody decided, which is how the deadlock ships.

The checks are textual (rule 1 strips comments; rule 2 reads them),
not an AST walk: they must catch lazy function-level imports and
closures built inside call arguments, and false positives are resolved
by the allowlist / a marker anyway.

Run from the repo root (or pass ``--root``)::

    python scripts/lint_collectives.py
"""

from __future__ import annotations

import argparse
import os
import re
import sys

# The single file allowed to touch the raw wire.
TRANSPORT_HOME = "wormhole_tpu/parallel/transport.py"

# Audited files outside TRANSPORT_HOME that legitimately reference
# multihost_utils. Every entry carries the reason. Deliberately EMPTY:
# the PR that unified the transport rewrote every call site against the
# stack, and new entries should be rare and argued.
ALLOWLIST: dict = {}

_PAT = re.compile(r"\bmultihost_utils\b")

# rule 2: collective call sites and their routing markers
_CALL_PAT = re.compile(
    r"\b(allreduce_tree|allgather_tree|broadcast_tree)\s*\(")
_MARKER_PAT = re.compile(r"#\s*transport:\s*(\w+)")
_ROUTES = ("engine", "direct", "mesh")
_MARKER_WINDOW = 3   # marker may sit up to this many lines above the call

# the retired two-marker form; flagged so stale markers don't linger as
# dead annotations that LOOK like routing decisions
_OLD_MARKER_PAT = re.compile(r"#\s*(ps-engine|bsp-direct):")


def _strip_comments(text: str) -> str:
    """Drop `#`-to-EOL per line (keeps line numbers aligned). Naive about
    `#` inside string literals — good enough for a lint whose false
    positives land in a human-reviewed allowlist."""
    return "\n".join(ln.split("#", 1)[0] for ln in text.splitlines())


def scan_file(path: str) -> list:
    """Return 1-based line numbers of multihost_utils references."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        text = _strip_comments(f.read())
    return [text.count("\n", 0, m.start()) + 1
            for m in _PAT.finditer(text)]


def scan_markers(path: str) -> list:
    """Rule 2: return ``(line, reason)`` for every collective call site
    without a valid ``# transport: <route>`` marker on the call line or
    the :data:`_MARKER_WINDOW` lines above it, plus every stale
    old-form marker left in the file."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        raw = f.read()
    raw_lines = raw.splitlines()
    code_lines = _strip_comments(raw).splitlines()
    out = []
    for i, ln in enumerate(raw_lines):
        if _OLD_MARKER_PAT.search(ln):
            out.append((i + 1, "retired marker form (use `# transport: "
                               "engine|direct|mesh`)"))
    for i, ln in enumerate(code_lines):
        m = _CALL_PAT.search(ln)
        if m is None:
            continue
        lo = max(0, i - _MARKER_WINDOW)
        marks = [_MARKER_PAT.search(r) for r in raw_lines[lo:i + 1]]
        marks = [mk for mk in marks if mk is not None]
        if not marks:
            out.append((i + 1, f"{m.group(1)} without a `# transport:` "
                               f"marker"))
        elif not any(mk.group(1) in _ROUTES for mk in marks):
            bad = ", ".join(sorted({mk.group(1) for mk in marks}))
            out.append((i + 1, f"{m.group(1)} marker route {bad!r} not in "
                               f"{'/'.join(_ROUTES)}"))
    return out


def run(root: str) -> int:
    """Scan ``root``/wormhole_tpu for violations; return a process rc."""
    pkg = os.path.join(root, "wormhole_tpu")
    if not os.path.isdir(pkg):
        print(f"lint_collectives: no wormhole_tpu package under {root!r}",
              file=sys.stderr)
        return 2
    violations = []
    unmarked = []
    seen_allowed = set()
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if rel == TRANSPORT_HOME:
                continue  # the one file that owns the raw wire
            if not rel.startswith("wormhole_tpu/parallel/"):
                unmarked.extend(f"{rel}:{ln}: {why}"
                                for ln, why in scan_markers(path))
            lines = scan_file(path)
            if not lines:
                continue
            if rel in ALLOWLIST:
                seen_allowed.add(rel)
            else:
                violations.extend(f"{rel}:{ln}" for ln in lines)
    for rel in sorted(set(ALLOWLIST) - seen_allowed):
        # stale entries are a warning, not a failure: deleting the last
        # reference from an audited file should not break the build
        print(f"lint_collectives: allowlist entry {rel} has no "
              f"multihost_utils references (stale?)", file=sys.stderr)
    if violations:
        print(f"lint_collectives: raw multihost transport outside "
              f"{TRANSPORT_HOME}:", file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        print("route the call through the transport stack "
              "(parallel/collectives.py allreduce_tree / allgather_tree "
              "/ broadcast_tree / host_local_to_global, or "
              "parallel/transport.py TransportStack) so it rides the "
              "layer stack and the comm byte counters, or add the file "
              "to ALLOWLIST in scripts/lint_collectives.py with a reason",
              file=sys.stderr)
        return 1
    if unmarked:
        print("lint_collectives: collective call sites without a valid "
              "routing marker:", file=sys.stderr)
        for v in unmarked:
            print(f"  {v}", file=sys.stderr)
        print("mark the site `# transport: engine` (it runs on the "
              "exchange engine's drain thread — ExchangeEngine.submit/"
              "exchange, e.g. via AsyncSGD._ctl), `# transport: direct` "
              "(it provably never coexists with a live engine) or "
              "`# transport: mesh` (host-side leg of the in-jit psum "
              f"path) within {_MARKER_WINDOW} lines above the call",
              file=sys.stderr)
        return 1
    print(f"lint_collectives: OK ({len(seen_allowed)} allowlisted files)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=".",
                    help="repo root containing wormhole_tpu/ "
                         "(default: cwd)")
    args = ap.parse_args(argv)
    return run(args.root)


if __name__ == "__main__":
    sys.exit(main())

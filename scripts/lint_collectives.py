#!/usr/bin/env python
"""Lint: no direct ``multihost_utils`` use outside wormhole_tpu/parallel/.

Every host-level DCN hop must go through parallel/collectives.py
(``allreduce_tree`` / ``allgather_tree`` / ``broadcast_tree`` /
``host_local_to_global``): that is where the ps-lite filter chain
(parallel/filters.py — KEY_CACHING / FIXING_FLOAT / COMPRESSING) and the
wire-byte accounting (``comm/bytes_raw`` etc.) live. A call site that
imports ``jax.experimental.multihost_utils`` directly bypasses both —
its payload ships unfiltered and its bytes vanish from the comm
counters — so this lint fails the build until the site is rewritten
against the wrappers or consciously allowlisted with a reason.

The check is textual (comments stripped), not an AST walk: it must
catch the module name inside lazy function-level imports and strings
being exec'd too, and false positives are resolved by the allowlist
anyway.

Run from the repo root (or pass ``--root``)::

    python scripts/lint_collectives.py
"""

from __future__ import annotations

import argparse
import os
import re
import sys

# Audited files outside parallel/ that legitimately reference
# multihost_utils. Every entry carries the reason. Deliberately EMPTY:
# the PR that introduced this lint rewrote every call site against the
# parallel/ wrappers, and new entries should be rare and argued.
ALLOWLIST: dict = {}

_PAT = re.compile(r"\bmultihost_utils\b")


def _strip_comments(text: str) -> str:
    """Drop `#`-to-EOL per line (keeps line numbers aligned). Naive about
    `#` inside string literals — good enough for a lint whose false
    positives land in a human-reviewed allowlist."""
    return "\n".join(ln.split("#", 1)[0] for ln in text.splitlines())


def scan_file(path: str) -> list:
    """Return 1-based line numbers of multihost_utils references."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        text = _strip_comments(f.read())
    return [text.count("\n", 0, m.start()) + 1
            for m in _PAT.finditer(text)]


def run(root: str) -> int:
    """Scan ``root``/wormhole_tpu for violations; return a process rc."""
    pkg = os.path.join(root, "wormhole_tpu")
    if not os.path.isdir(pkg):
        print(f"lint_collectives: no wormhole_tpu package under {root!r}",
              file=sys.stderr)
        return 2
    violations = []
    seen_allowed = set()
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if rel.startswith("wormhole_tpu/parallel/"):
                continue  # parallel/ owns the raw transport
            lines = scan_file(path)
            if not lines:
                continue
            if rel in ALLOWLIST:
                seen_allowed.add(rel)
            else:
                violations.extend(f"{rel}:{ln}" for ln in lines)
    for rel in sorted(set(ALLOWLIST) - seen_allowed):
        # stale entries are a warning, not a failure: deleting the last
        # reference from an audited file should not break the build
        print(f"lint_collectives: allowlist entry {rel} has no "
              f"multihost_utils references (stale?)", file=sys.stderr)
    if violations:
        print("lint_collectives: direct multihost_utils use outside "
              "wormhole_tpu/parallel/:", file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        print("route the call through parallel/collectives.py "
              "(allreduce_tree / allgather_tree / broadcast_tree / "
              "host_local_to_global) so it rides the filter chain and "
              "the comm byte counters, or add the file to ALLOWLIST in "
              "scripts/lint_collectives.py with a reason",
              file=sys.stderr)
        return 1
    print(f"lint_collectives: OK ({len(seen_allowed)} allowlisted files)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=".",
                    help="repo root containing wormhole_tpu/ "
                         "(default: cwd)")
    args = ap.parse_args(argv)
    return run(args.root)


if __name__ == "__main__":
    sys.exit(main())

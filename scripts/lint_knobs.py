#!/usr/bin/env python
"""Lint: every Config knob is documented; every metric name is unique.

Two rules, both born of the obs/ PR:

1. **Knob coverage** — every field of ``wormhole_tpu.utils.config.Config``
   must appear somewhere under ``docs/*.md`` (the reference table lives
   in docs/config.md). A knob nobody can discover is a knob nobody can
   turn; the reference ships config.proto with inline docs for the same
   reason. Fields are extracted by AST walk (no jax import needed), so
   the lint runs anywhere.

2. **Metric-name uniqueness** — every literal metric name declared
   against a registry (``.counter("name")`` / ``.gauge("name")`` /
   ``.histogram("name")`` in ``wormhole_tpu/``) must be declared at
   exactly one site. Two sites declaring the same name silently merge
   their streams (Registry returns the existing metric), which is the
   observability version of two writers on one file. The registry
   enforces kind-mismatch at runtime; this lint catches the same-kind
   collision that runtime cannot distinguish from intent.

Run from the repo root (or pass ``--root``)::

    python scripts/lint_knobs.py
"""

from __future__ import annotations

import argparse
import ast
import glob
import os
import re
import sys

# Config fields that may legitimately stay out of docs/. Every entry
# carries a reason; keep this empty-by-default bias — documenting the
# knob is almost always cheaper than explaining why not.
KNOB_ALLOWLIST = {}

# `.counter("x")` / `.gauge("x")` / `.histogram("x")` with a literal
# first argument — declaration sites the uniqueness rule applies to.
# Computed names (`prefix + k`) are adapter plumbing, not declarations.
_METRIC_PAT = re.compile(
    r"\.(counter|gauge|histogram)\(\s*['\"]([^'\"]+)['\"]")


def config_fields(root: str) -> list:
    """Config's annotated field names, by AST (import-free)."""
    path = os.path.join(root, "wormhole_tpu", "utils", "config.py")
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), path)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            return [st.target.id for st in node.body
                    if isinstance(st, ast.AnnAssign)
                    and isinstance(st.target, ast.Name)]
    raise RuntimeError(f"no Config class found in {path}")


def documented_text(root: str) -> str:
    parts = []
    for p in sorted(glob.glob(os.path.join(root, "docs", "*.md"))):
        with open(p, "r", encoding="utf-8", errors="replace") as f:
            parts.append(f.read())
    return "\n".join(parts)


def undocumented_knobs(root: str) -> list:
    docs = documented_text(root)
    missing = []
    for name in config_fields(root):
        if name in KNOB_ALLOWLIST:
            continue
        # word-boundary match: `minibatch` in prose, a table row, or a
        # `key=value` example all count; substrings of other words don't
        if not re.search(rf"\b{re.escape(name)}\b", docs):
            missing.append(name)
    return missing


def metric_sites(root: str) -> dict:
    """name -> ["file:line", ...] of literal metric declarations."""
    sites: dict = {}
    pkg = os.path.join(root, "wormhole_tpu")
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, "r", encoding="utf-8",
                      errors="replace") as f:
                text = f.read()
            for m in _METRIC_PAT.finditer(text):
                ln = text.count("\n", 0, m.start()) + 1
                sites.setdefault(m.group(2), []).append(f"{rel}:{ln}")
    return sites


def duplicate_metrics(root: str) -> dict:
    return {name: where for name, where in metric_sites(root).items()
            if len(where) > 1}


def run(root: str) -> int:
    """Run both rules; return a process rc."""
    if not os.path.isdir(os.path.join(root, "wormhole_tpu")):
        print(f"lint_knobs: no wormhole_tpu package under {root!r}",
              file=sys.stderr)
        return 2
    rc = 0
    missing = undocumented_knobs(root)
    if missing:
        rc = 1
        print("lint_knobs: Config fields missing from docs/*.md:",
              file=sys.stderr)
        for name in missing:
            print(f"  {name}", file=sys.stderr)
        print("add a row to docs/config.md (or, with a reason, to "
              "KNOB_ALLOWLIST in scripts/lint_knobs.py)",
              file=sys.stderr)
    dups = duplicate_metrics(root)
    if dups:
        rc = 1
        print("lint_knobs: metric names declared at multiple sites:",
              file=sys.stderr)
        for name, where in sorted(dups.items()):
            print(f"  {name}: {', '.join(where)}", file=sys.stderr)
        print("declare each metric once and pass the object around "
              "(two declaration sites silently merge their streams)",
              file=sys.stderr)
    if rc == 0:
        n = len(config_fields(root))
        print(f"lint_knobs: OK ({n} knobs documented, "
              f"{len(metric_sites(root))} unique metric names)")
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=".",
                    help="repo root containing wormhole_tpu/ and docs/ "
                         "(default: cwd)")
    args = ap.parse_args(argv)
    return run(args.root)


if __name__ == "__main__":
    sys.exit(main())

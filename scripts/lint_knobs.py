#!/usr/bin/env python
"""Lint: every Config knob is documented; every metric name is unique.

Thin shim: the checker now lives on the shared analysis engine as
``wormhole_tpu.analysis.checkers.knobs`` (WH-KNOB) and also runs via
``scripts/lint.py``. This script re-exports the legacy module API
(``config_fields``, ``metric_sites``, ``duplicate_metrics``, ``run``,
...) and keeps the legacy CLI and output.

Run from the repo root (or pass ``--root``)::

    python scripts/lint_knobs.py
"""

from __future__ import annotations

import argparse
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from wormhole_tpu.analysis.checkers.knobs import (  # noqa: E402,F401
    KNOB_ALLOWLIST,
    KnobChecker,
    _METRIC_PAT,
    config_fields,
    documented_text,
    duplicate_metrics,
    metric_sites,
    run,
    undocumented_knobs,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=".",
                    help="repo root containing wormhole_tpu/ and docs/ "
                         "(default: cwd)")
    args = ap.parse_args(argv)
    return run(args.root)


if __name__ == "__main__":
    sys.exit(main())

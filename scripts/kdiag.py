"""Stage-by-stage timing of the fwd tile kernel (dev diagnostic).

Builds cumulative variants of the fwd kernel to locate where the time
goes: D0 relayout+astype only, D1 +ohhi build, D2 +gather matmul,
D3 +pick matmul, D4 full kernel (= tilemm fwd). Results are WRONG for
all but D4 — timing only.
"""
from __future__ import annotations

import sys
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")
from wormhole_tpu.ops import tilemm  # noqa: E402
from wormhole_tpu.ops.tilemm import (  # noqa: E402
    A_HI, B_LO, RH, RL, HI_SH, HI_M, LO_SH, LO_M, RLO_SH, RLO_M,
    RHI_SH, RHI_M, _oh_rep, _mask_sel, _ohT_vec)

NB = 1 << 22
ROWS = 98304
NNZ = 39


from scripts.ktune import _force, timeit  # noqa: E402  (shared harness)


def _kernel(spec, stage, pw_ref, w_ref, mg_ref):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        mg_ref[:] = jnp.zeros_like(mg_ref)

    S, GS, C, N = spec.subblocks, spec.group, spec.cap, spec.n
    ones_pick = jnp.ones((B_LO, RL), jnp.bfloat16)
    for g in range(S // GS):
        mgs = [mg_ref[g * GS + j] for j in range(GS)]
        for tb in range(spec.tiles_step):
            wt = w_ref[tb]
            pc = pw_ref[tb, g].astype(jnp.int32)
            rep = pc[:, None]
            if stage == 0:          # relayout + one astype pass
                x = (rep & 127).astype(jnp.bfloat16) * ones_pick[:1]
                for j in range(GS):
                    mgs[j] += x[j * 64:(j + 1) * 64, :].astype(jnp.float32)
                continue
            ohhi = _oh_rep(rep, HI_SH, HI_M, N, 128)
            if stage == 1:          # + ohhi build
                for j in range(GS):
                    mgs[j] += ohhi[j * 64:(j + 1) * 64, :].astype(
                        jnp.float32)
                continue
            if stage == 21:         # gather vs a CONSTANT rhs
                m = jnp.dot(ohhi, ones_pick,
                            preferred_element_type=jnp.float32)
                for j in range(GS):
                    mgs[j] += m[j * 64:(j + 1) * 64, :]
                continue
            if stage == 22:         # gather, rhs = wt of tile 0 only
                m = jnp.dot(ohhi, w_ref[0],
                            preferred_element_type=jnp.float32)
                for j in range(GS):
                    mgs[j] += m[j * 64:(j + 1) * 64, :]
                continue
            m = jnp.dot(ohhi, wt, preferred_element_type=jnp.float32)
            if stage == 23:         # TWO varying-rhs gathers
                m2 = jnp.dot(ohhi, w_ref[(tb + 1) % spec.tiles_step],
                             preferred_element_type=jnp.float32)
                for j in range(GS):
                    mgs[j] += m[j * 64:(j + 1) * 64, :] \
                        + m2[j * 64:(j + 1) * 64, :]
                continue
            if stage == 2:          # + gather matmul
                for j in range(GS):
                    mgs[j] += m[j * 64:(j + 1) * 64, :]
                continue
            wp = jnp.dot(_mask_sel(rep, LO_SH, LO_M, m), ones_pick,
                         preferred_element_type=jnp.float32)
            if stage == 3:          # + pick matmul
                for j in range(GS):
                    mgs[j] += wp[j * 64:(j + 1) * 64, :]
                continue
            rhs = _mask_sel(rep, RLO_SH, RLO_M, wp)
            for j in range(GS):
                rhiT = _ohT_vec(pc[j * C:(j + 1) * C], RHI_SH, RHI_M,
                                RH, C)
                mgs[j] += jnp.dot(rhiT, rhs[j * C:(j + 1) * C],
                                  preferred_element_type=jnp.float32)
        for j in range(GS):
            mg_ref[g * GS + j] = mgs[j]


def build(spec, stage):
    T, TB = spec.tiles, spec.tiles_step
    SG, N, S = spec.subblocks // spec.group, spec.n, spec.subblocks

    @jax.jit
    def fwd(pw, w):
        wt = w.reshape(T, A_HI, B_LO).astype(jnp.bfloat16)
        return pl.pallas_call(
            partial(_kernel, spec, stage),
            grid=(T // TB,),
            in_specs=[
                pl.BlockSpec((TB, SG, N), lambda t: (t, 0, 0)),
                pl.BlockSpec((TB, A_HI, B_LO), lambda t: (t, 0, 0)),
            ],
            out_specs=pl.BlockSpec((S, RH, RL), lambda t: (0, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((S, RH, RL), jnp.float32),
            compiler_params=pltpu.CompilerParams(
                vmem_limit_bytes=100 * 1024 * 1024),
        )(pw, wt)

    return fwd


def main():
    from wormhole_tpu.data.crec import default_cap
    spec = tilemm.make_spec(NB, ROWS // tilemm.RSUB, default_cap(NNZ, NB))
    print("spec:", spec)
    rng = np.random.default_rng(0)
    buckets = rng.integers(0, NB, size=ROWS * NNZ, dtype=np.int64)
    rows = np.repeat(np.arange(ROWS, dtype=np.int64), NNZ)
    pw_np, _, _ = tilemm.encode_block(buckets, rows, spec)
    w_np = rng.normal(0, 0.1, NB).astype(np.float32)
    pw, w = jax.device_put(pw_np), jax.device_put(w_np)
    stages = [int(s) for s in sys.argv[1:]] or [0, 1, 2, 3, 4]
    prev = 0.0
    for st in stages:
        t = timeit(build(spec, st), pw, w)
        print(f"stage {st}: {t*1e3:7.3f} ms  (delta {(t-prev)*1e3:+7.3f})")
        prev = t


if __name__ == "__main__":
    main()

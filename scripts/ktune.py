"""Kernel tuning harness for ops/tilemm.py — times fwd/bwd separately
on real TPU hardware, checks them against the exact numpy oracle, and
sweeps tiles_step. Not part of the bench; a dev tool.

Usage: python scripts/ktune.py [reps] [tb1,tb2,...]
       python scripts/ktune.py --kernel fused|split|both|cached|both3 \
           [--windows N] [--burn N] [reps]

``--kernel`` times the full FTRL train step instead of the bare
fwd/bwd pair; ``both`` is the A/B mode — each window times split and
fused back-to-back, so the per-window ratio is contention-robust on
the shared chip (the round-4/5 interleaved methodology) even when the
absolute times are not. ``cached`` drives the fused step with the
phase-shared one-hot cache forced on; ``both3`` is the round-8
three-way interleave: each window runs split, fused, and fused+cache
back-to-back and reports both per-window ratios. The cached modes
drop to a narrow-block geometry (one subblock, nnz=16, same bucket
space) where the resolver's auto genuinely admits the cache — at the
default wide geometry the planes need ~2.1 GB of VMEM and the kernel
would not compile on a TPU, so there is nothing to measure there.
"""
from __future__ import annotations

import dataclasses
import sys
import time

import jax
import numpy as np

sys.path.insert(0, ".")

from wormhole_tpu.ops import tilemm  # noqa: E402

NB = 1 << 22
ROWS = 98304
NNZ = 39


def _force(o):
    """Force real completion: a D2H read of one element (tunnel futures
    can fake block_until_ready; VERDICT r2)."""
    float(np.asarray(jax.tree_util.tree_leaves(o)[0].ravel()[0]))


def timeit(fn, *args, reps=15, burn=100, windows=10):
    """Min-of-windows: the tunneled chip shows time-varying contention /
    throttle (measured round 4: +-25%% swings, later-in-process windows
    slower), so the MIN over several short windows approximates the
    uncontended kernel time and is what A/B decisions should use."""
    o = None
    for _ in range(burn):
        o = fn(*args)
    _force(o)
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        o = None
        for _ in range(reps):
            o = fn(*args)
        _force(o)
        best = min(best, (time.perf_counter() - t0) / reps)
    return best


def _build_ab_steps(spec, which):
    """Jitted full train steps for the --kernel A/B: the split oracle
    (fwd pallas_call -> XLA dual -> bwd pallas_call -> XLA push) and
    the fused one-grid step with the in-place FTRL update."""
    import jax.numpy as jnp

    from wormhole_tpu.learners.handles import FTRLHandle, LearnRate
    from wormhole_tpu.ops.loss import create_loss
    from wormhole_tpu.ops.penalty import L1L2

    handle = FTRLHandle(penalty=L1L2(1.0, 0.1), lr=LearnRate(0.1, 1.0))
    _, dual_fn = create_loss("logit")
    steps = {}
    if which in ("split", "both", "both3"):
        @jax.jit
        def split_step(pw, s32, labels, mask):
            w = handle.weights(s32)
            margin = tilemm.forward_margins(pw, w, spec)
            dual = dual_fn(margin, labels, mask)
            grad = tilemm.backward_grad(pw, dual, spec)
            new = handle.push(s32, grad, jnp.float32(0), jnp.float32(0))
            return margin, new
        steps["split"] = split_step
    if which in ("fused", "both", "both3"):
        @jax.jit
        def fused_step(pw, s32, labels, mask):
            return tilemm.fused_step_update(pw, s32, labels, mask,
                                            spec, "logit", handle)
        steps["fused"] = fused_step
    if which in ("cached", "both3"):
        # cache forced past the resolver's VMEM budget model — this is
        # the measurement mode the `on` knob exists for
        @jax.jit
        def cached_step(pw, s32, labels, mask):
            return tilemm.fused_step_update(pw, s32, labels, mask,
                                            spec, "logit", handle,
                                            cache=True)
        steps["cached"] = cached_step
    return handle, steps


def _kernel_ab(spec, pw, which, reps, windows=10, burn=20):
    """Time the resolved train-step kernels; in ``both`` mode each
    window runs split then fused back-to-back and the reported ratio
    is the median of the per-window ratios."""
    rng = np.random.default_rng(1)
    handle, steps = _build_ab_steps(spec, which)
    s32 = jax.device_put(
        rng.normal(0, 0.1, (spec.nb, handle.val_len)).astype(np.float32))
    labels = jax.device_put(
        (rng.random(spec.block_rows) < 0.5).astype(np.float32))
    mask = jax.device_put(np.ones(spec.block_rows, np.float32))
    for name, fn in steps.items():
        o = None
        for _ in range(burn):
            o = fn(pw, s32, labels, mask)
        _force(o)
    best = {name: float("inf") for name in steps}
    ratios = {"split/fused": [], "fused/cached": []}
    for _ in range(windows):
        win = {}
        for name, fn in steps.items():
            t0 = time.perf_counter()
            o = None
            for _ in range(reps):
                o = fn(pw, s32, labels, mask)
            _force(o)
            win[name] = (time.perf_counter() - t0) / reps
            best[name] = min(best[name], win[name])
        if "split" in win and "fused" in win:
            ratios["split/fused"].append(win["split"] / win["fused"])
        if "fused" in win and "cached" in win:
            ratios["fused/cached"].append(win["fused"] / win["cached"])
    for name, t in best.items():
        print(f"{name:6s} step {t*1e3:7.3f} ms -> "
              f"{spec.block_rows/t/1e6:.2f} M ex/s")
    for label, rs in ratios.items():
        if rs:
            print(f"{label} ratio: median {np.median(rs):.3f} "
                  f"min {min(rs):.3f} max {max(rs):.3f} "
                  f"({len(rs)} interleaved windows)")


def main():
    args = list(sys.argv[1:])
    kernel = None
    if "--kernel" in args:
        i = args.index("--kernel")
        kernel = args[i + 1]
        if kernel not in ("fused", "split", "both", "cached", "both3"):
            raise SystemExit(f"--kernel must be fused|split|both|"
                             f"cached|both3, got {kernel!r}")
        del args[i:i + 2]
    # single-core hosts drive the fused kernel through interpret mode
    # at ~10s/step — the TPU defaults (10 windows, 20-step burn) would
    # run for the better part of an hour there
    windows, burn = 10, 20
    if "--windows" in args:
        i = args.index("--windows")
        windows = int(args[i + 1])
        del args[i:i + 2]
    if "--burn" in args:
        i = args.index("--burn")
        burn = int(args[i + 1])
        del args[i:i + 2]
    reps = int(args[0]) if len(args) > 0 else 20
    tbs = ([int(x) for x in args[1].split(",")]
           if len(args) > 1 else [])
    from wormhole_tpu.data.crec import default_cap
    rows_n, nnz = ROWS, NNZ
    if kernel in ("cached", "both3"):
        # cache-admissible narrow geometry (see module docstring)
        rows_n, nnz = tilemm.RSUB, 16
    spec = tilemm.make_spec(NB, rows_n // tilemm.RSUB,
                            default_cap(nnz, NB))
    print("spec:", spec)

    rng = np.random.default_rng(0)
    buckets = rng.integers(0, NB, size=rows_n * nnz, dtype=np.int64)
    rows = np.repeat(np.arange(rows_n, dtype=np.int64), nnz)
    pw_np, ovb, _ = tilemm.encode_block(buckets, rows, spec)
    print(f"overflow pairs: {len(ovb)}")
    w_np = rng.normal(0, 0.1, NB).astype(np.float32)
    dual_np = rng.normal(0, 1.0, rows_n).astype(np.float32)
    # device-resident operands: numpy args would re-upload ~90 MB per
    # call through the host transport and swamp the kernel timing
    pw, w, dual = (jax.device_put(x) for x in (pw_np, w_np, dual_np))

    if kernel is not None:
        # full-train-step A/B on the same encoded block; overflow pairs
        # are dropped from BOTH paths (the fused kernel is dense-only,
        # so the comparison stays operand-identical)
        _kernel_ab(spec, pw, kernel, reps, windows=windows, burn=burn)
        return

    slots = spec.tiles * spec.subblocks * spec.cap
    # MXU N-row pass floor: passes x slots x 16384 MAC @ 98.5e12 MAC/s
    floor = 3 * slots * 16384 / 98.5e12

    fwd, bwd = tilemm._build_fwd(spec), tilemm._build_bwd(spec)
    mg = np.asarray(fwd(pw, w))
    g = np.asarray(bwd(pw, dual))
    om = tilemm.forward_margins_ref(buckets, rows, w_np, ROWS)
    og = tilemm.backward_grad_ref(buckets, rows, dual_np, NB)
    print(f"max|dmargin|={np.max(np.abs(mg - om)):.3e} "
          f"max|dgrad|={np.max(np.abs(g - og)):.3e} (bf16-value rounding)")
    t_f = timeit(fwd, pw, w, reps=reps)
    t_b = timeit(bwd, pw, dual, reps=reps)
    tot = t_f + t_b
    print(f"fwd {t_f*1e3:7.3f} ms (floor-frac {floor/t_f:.3f})  "
          f"bwd {t_b*1e3:7.3f} ms (floor-frac {floor/t_b:.3f})  "
          f"tot {tot*1e3:.2f} ms -> {ROWS/tot/1e6:.2f} M ex/s")

    for tb in tbs:
        f = spec.fuse            # keep the production fuse when tb
        while f > 1 and tb % f:  # allows it, else largest divisor —
            f //= 2              # sweep rows stay comparable to base
        sp = dataclasses.replace(spec, tiles_step=tb, fuse=f)
        f2, b2 = tilemm._build_fwd(sp), tilemm._build_bwd(sp)
        t_f = timeit(f2, pw, w, reps=reps)
        t_b = timeit(b2, pw, dual, reps=reps)
        tot = t_f + t_b
        print(f"TB={tb:2d}: fwd {t_f*1e3:7.3f} bwd {t_b*1e3:7.3f} "
              f"tot {tot*1e3:.2f} ms -> {ROWS/tot/1e6:.2f} M ex/s")


if __name__ == "__main__":
    main()

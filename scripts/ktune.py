"""Kernel tuning harness for ops/tilemm.py — times fwd/bwd separately
on real TPU hardware, checks them against the exact numpy oracle, and
sweeps tiles_step. Not part of the bench; a dev tool.

Usage: python scripts/ktune.py [reps] [tb1,tb2,...]
"""
from __future__ import annotations

import dataclasses
import sys
import time

import jax
import numpy as np

sys.path.insert(0, ".")

from wormhole_tpu.ops import tilemm  # noqa: E402

NB = 1 << 22
ROWS = 98304
NNZ = 39


def _force(o):
    """Force real completion: a D2H read of one element (tunnel futures
    can fake block_until_ready; VERDICT r2)."""
    float(np.asarray(jax.tree_util.tree_leaves(o)[0].ravel()[0]))


def timeit(fn, *args, reps=15, burn=100, windows=10):
    """Min-of-windows: the tunneled chip shows time-varying contention /
    throttle (measured round 4: +-25%% swings, later-in-process windows
    slower), so the MIN over several short windows approximates the
    uncontended kernel time and is what A/B decisions should use."""
    o = None
    for _ in range(burn):
        o = fn(*args)
    _force(o)
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        o = None
        for _ in range(reps):
            o = fn(*args)
        _force(o)
        best = min(best, (time.perf_counter() - t0) / reps)
    return best


def main():
    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 20
    tbs = ([int(x) for x in sys.argv[2].split(",")]
           if len(sys.argv) > 2 else [])
    from wormhole_tpu.data.crec import default_cap
    spec = tilemm.make_spec(NB, ROWS // tilemm.RSUB, default_cap(NNZ, NB))
    print("spec:", spec)

    rng = np.random.default_rng(0)
    buckets = rng.integers(0, NB, size=ROWS * NNZ, dtype=np.int64)
    rows = np.repeat(np.arange(ROWS, dtype=np.int64), NNZ)
    pw_np, ovb, _ = tilemm.encode_block(buckets, rows, spec)
    print(f"overflow pairs: {len(ovb)}")
    w_np = rng.normal(0, 0.1, NB).astype(np.float32)
    dual_np = rng.normal(0, 1.0, ROWS).astype(np.float32)
    # device-resident operands: numpy args would re-upload ~90 MB per
    # call through the host transport and swamp the kernel timing
    pw, w, dual = (jax.device_put(x) for x in (pw_np, w_np, dual_np))

    slots = spec.tiles * spec.subblocks * spec.cap
    # MXU N-row pass floor: passes x slots x 16384 MAC @ 98.5e12 MAC/s
    floor = 3 * slots * 16384 / 98.5e12

    fwd, bwd = tilemm._build_fwd(spec), tilemm._build_bwd(spec)
    mg = np.asarray(fwd(pw, w))
    g = np.asarray(bwd(pw, dual))
    om = tilemm.forward_margins_ref(buckets, rows, w_np, ROWS)
    og = tilemm.backward_grad_ref(buckets, rows, dual_np, NB)
    print(f"max|dmargin|={np.max(np.abs(mg - om)):.3e} "
          f"max|dgrad|={np.max(np.abs(g - og)):.3e} (bf16-value rounding)")
    t_f = timeit(fwd, pw, w, reps=reps)
    t_b = timeit(bwd, pw, dual, reps=reps)
    tot = t_f + t_b
    print(f"fwd {t_f*1e3:7.3f} ms (floor-frac {floor/t_f:.3f})  "
          f"bwd {t_b*1e3:7.3f} ms (floor-frac {floor/t_b:.3f})  "
          f"tot {tot*1e3:.2f} ms -> {ROWS/tot/1e6:.2f} M ex/s")

    for tb in tbs:
        f = spec.fuse            # keep the production fuse when tb
        while f > 1 and tb % f:  # allows it, else largest divisor —
            f //= 2              # sweep rows stay comparable to base
        sp = dataclasses.replace(spec, tiles_step=tb, fuse=f)
        f2, b2 = tilemm._build_fwd(sp), tilemm._build_bwd(sp)
        t_f = timeit(f2, pw, w, reps=reps)
        t_b = timeit(b2, pw, dual, reps=reps)
        tot = t_f + t_b
        print(f"TB={tb:2d}: fwd {t_f*1e3:7.3f} bwd {t_b*1e3:7.3f} "
              f"tot {tot*1e3:.2f} ms -> {ROWS/tot/1e6:.2f} M ex/s")


if __name__ == "__main__":
    main()

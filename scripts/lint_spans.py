#!/usr/bin/env python
"""Lint: every span name is declared once in the central span table.

The step ledger (``wormhole_tpu/obs/ledger.py``) folds trace spans into
wall-time buckets by name. A renamed instrumentation site would silently
fall out of its bucket and into ``other``/``unattributed`` — the
observability version of the silent metric fork ``lint_knobs`` guards
against. Two rules:

1. **Declaration coverage** — every span name used at an
   instrumentation site (literal or literal-prefixed first argument to
   ``Timer.scope`` / ``trace.span`` / ``trace.complete`` under
   ``wormhole_tpu/``) must resolve through ``SPAN_TABLE``: an exact
   entry, a ``prefix*`` pattern, the ``eval_`` fold, the ``_stall``
   rule, or the DeviceFeed ``<feed>:<stage>`` stage rule. Fully dynamic
   names (``f"{self.name}:{label}"`` — the DeviceFeed relay and
   ``Timer.scope``'s own forwarding) carry no literal and are resolved
   at runtime by the same stage rules; this lint covers every site a
   rename could silently break.
2. **Single declaration site** — ``SPAN_TABLE`` itself is assigned at
   exactly one place under ``wormhole_tpu/``, and its dict literal has
   no duplicate keys (Python would silently keep the last one).

Run from the repo root (or pass ``--root``)::

    python scripts/lint_spans.py
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys

# literal (or `pfx + "literal"`) first args to Timer.scope — the timer
# relays the name into trace.complete verbatim (modulo the prefix,
# which instrumentation only uses for the eval_ fold)
_SCOPE_PAT = re.compile(r"\.scope\(\s*(?:\w+\s*\+\s*)?['\"]([^'\"]+)['\"]")
# literal span/complete names
_SPAN_LIT_PAT = re.compile(
    r"trace\.(?:span|complete)\(\s*['\"]([^'\"]+)['\"]")
# f-string span/complete names with a literal prefix before the first
# placeholder (e.g. f"collective:allreduce_{op}") — the prefix must
# match a `prefix*` table pattern
_SPAN_FPAT = re.compile(
    r"trace\.(?:span|complete)\(\s*f['\"]([^'\"{}]+)\{")


def span_table(root: str):
    """(keys, duplicate_keys, declaration_sites) of SPAN_TABLE, by AST
    walk over ``wormhole_tpu/`` (import-free, works on synthetic trees)."""
    keys: list = []
    dups: list = []
    sites: list = []
    pkg = os.path.join(root, "wormhole_tpu")
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, "r", encoding="utf-8",
                      errors="replace") as f:
                try:
                    tree = ast.parse(f.read(), path)
                except SyntaxError:
                    continue
            for node in ast.walk(tree):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets = [node.target]
                if not any(isinstance(t, ast.Name)
                           and t.id == "SPAN_TABLE" for t in targets):
                    continue
                sites.append(f"{rel}:{node.lineno}")
                val = node.value
                if isinstance(val, ast.Dict):
                    seen = set()
                    for k in val.keys:
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str):
                            if k.value in seen:
                                dups.append(k.value)
                            seen.add(k.value)
                            keys.append(k.value)
    return keys, dups, sites


def span_sites(root: str) -> dict:
    """(name, is_prefix) -> ["file:line", ...] of span instrumentation
    sites with a literal (or literal-prefixed) name."""
    sites: dict = {}
    pkg = os.path.join(root, "wormhole_tpu")
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, "r", encoding="utf-8",
                      errors="replace") as f:
                text = f.read()
            for pat, is_prefix in ((_SCOPE_PAT, False),
                                   (_SPAN_LIT_PAT, False),
                                   (_SPAN_FPAT, True)):
                for m in pat.finditer(text):
                    ln = text.count("\n", 0, m.start()) + 1
                    sites.setdefault((m.group(1), is_prefix),
                                     []).append(f"{rel}:{ln}")
    return sites


def _resolves(name: str, is_prefix: bool, keys: list) -> bool:
    """Mirror of obs.ledger.span_bucket's matching rules, against the
    AST-extracted table (so synthetic test trees lint standalone)."""
    if is_prefix:
        # an f-string prefix matches any * pattern on the same stem
        return any(k.endswith("*")
                   and (k[:-1].startswith(name) or name.startswith(k[:-1]))
                   for k in keys)
    if name in keys:
        return True
    if name.startswith("eval_"):
        return _resolves(name[5:], False, keys)
    if name.endswith("_stall"):
        return True
    if any(k.endswith("*") and name.startswith(k[:-1]) for k in keys):
        return True
    if ":" in name:
        return name.rsplit(":", 1)[1] in keys
    return False


def undeclared_spans(root: str) -> dict:
    keys, _dups, _sites = span_table(root)
    return {name: where
            for (name, is_prefix), where in sorted(span_sites(root).items())
            if not _resolves(name, is_prefix, keys)}


def run(root: str) -> int:
    if not os.path.isdir(os.path.join(root, "wormhole_tpu")):
        print(f"lint_spans: no wormhole_tpu package under {root!r}",
              file=sys.stderr)
        return 2
    rc = 0
    keys, dups, decl_sites = span_table(root)
    if len(decl_sites) != 1:
        rc = 1
        print(f"lint_spans: SPAN_TABLE declared at {len(decl_sites)} "
              f"sites (want exactly 1): {', '.join(decl_sites) or 'none'}",
              file=sys.stderr)
    if dups:
        rc = 1
        print("lint_spans: duplicate SPAN_TABLE keys (the dict literal "
              "silently keeps the last):", file=sys.stderr)
        for k in dups:
            print(f"  {k}", file=sys.stderr)
    missing = undeclared_spans(root)
    if missing:
        rc = 1
        print("lint_spans: span names used but not declared in "
              "SPAN_TABLE (obs/ledger.py):", file=sys.stderr)
        for name, where in sorted(missing.items()):
            print(f"  {name}: {', '.join(where)}", file=sys.stderr)
        print("add the span to SPAN_TABLE with its ledger bucket — an "
              "undeclared span falls out of the wall-time attribution",
              file=sys.stderr)
    if rc == 0:
        n_sites = sum(len(w) for w in span_sites(root).values())
        print(f"lint_spans: OK ({n_sites} instrumentation sites resolve "
              f"through {len(keys)} table entries)")
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=".",
                    help="repo root containing wormhole_tpu/ "
                         "(default: cwd)")
    args = ap.parse_args(argv)
    return run(args.root)


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Lint: every span name is declared once in the central span table.

Thin shim: the checker now lives on the shared analysis engine as
``wormhole_tpu.analysis.checkers.spans`` (WH-SPAN) and also runs via
``scripts/lint.py``. This script re-exports the legacy module API
(``span_table``, ``span_sites``, ``_resolves``, ``undeclared_spans``,
``run``) and keeps the legacy CLI and output.

Run from the repo root (or pass ``--root``)::

    python scripts/lint_spans.py
"""

from __future__ import annotations

import argparse
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from wormhole_tpu.analysis.checkers.spans import (  # noqa: E402,F401
    SpanChecker,
    _SCOPE_PAT,
    _SPAN_FPAT,
    _SPAN_LIT_PAT,
    _resolves,
    run,
    span_sites,
    span_table,
    undeclared_spans,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=".",
                    help="repo root containing wormhole_tpu/ "
                         "(default: cwd)")
    args = ap.parse_args(argv)
    return run(args.root)


if __name__ == "__main__":
    sys.exit(main())

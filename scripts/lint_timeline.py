#!/usr/bin/env python
"""Lint: every timeline series name is declared once in SERIES_TABLE.

Thin shim: the checker now lives on the shared analysis engine as
``wormhole_tpu.analysis.checkers.timeline`` (WH-TIMELINE) and also
runs via ``scripts/lint.py``. This script re-exports the legacy module
API (``series_table``, ``metric_names``, ``objective_series``,
``derived_suffixes``, ``record_fields``, ``_resolves``, ``run``) and
keeps the legacy CLI and output.

Run from the repo root (or pass ``--root``)::

    python scripts/lint_timeline.py
"""

from __future__ import annotations

import argparse
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from wormhole_tpu.analysis.checkers.timeline import (  # noqa: E402,F401
    TimelineChecker,
    _METRIC_PAT,
    _SUFFIX_PAT,
    _resolves,
    derived_suffixes,
    metric_names,
    objective_series,
    record_fields,
    run,
    series_table,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=".",
                    help="repo root containing wormhole_tpu/ "
                         "(default: cwd)")
    args = ap.parse_args(argv)
    return run(args.root)


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Lint: every timeline series name is declared once in SERIES_TABLE.

The timeline plane (``wormhole_tpu/obs/timeline.py``) emits per-sample
series the SLO tracker (``obs/slo.py``), ``timeline.summarize``, and
``bench_check.py --slo`` read back by name. A renamed series — or an
SLO objective pointed at a metric that no longer exists — fails
*silently*: the objective just never sees a value, and the burn rate
stays 0 forever. Same failure class ``lint_spans.py`` guards for span
names and ``lint_knobs.py`` for metric names; same cure:

1. **Single declaration site** — ``SERIES_TABLE`` is assigned at
   exactly one place under ``wormhole_tpu/`` and its dict literal has
   no duplicate keys (Python silently keeps the last one).
2. **Objective coverage** — every literal series name handed to an
   ``Objective(...)`` under ``wormhole_tpu/`` must resolve: an exact
   ``SERIES_TABLE`` entry, a registry metric name (the lint_knobs
   declaration sites), or a declared ``*suffix`` derived rule over a
   registry metric (``serve/latency_s_p99`` = histogram + ``*_p99``).
3. **Derived-suffix coverage** — every literal ``+ "_suffix"`` series
   emission in ``obs/timeline.py`` must match a ``*suffix`` entry.
4. **Field coverage** — every keyword the sampler stamps through
   ``Registry.record(...)`` in ``obs/timeline.py`` must be declared a
   ``field`` entry (as must the ``ts``/``mono`` stamps record adds).

Run from the repo root (or pass ``--root``)::

    python scripts/lint_timeline.py
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys

# registry metric declaration sites (the lint_knobs contract)
_METRIC_PAT = re.compile(
    r"\.(?:counter|gauge|histogram)\(\s*['\"]([^'\"]+)['\"]")
# literal derived-suffix concatenations in the sampler
_SUFFIX_PAT = re.compile(r"\+\s*['\"](_[a-z0-9]+)['\"]")


def _walk_py(root: str):
    pkg = os.path.join(root, "wormhole_tpu")
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                path = os.path.join(dirpath, fn)
                yield path, os.path.relpath(path, root).replace(
                    os.sep, "/")


def series_table(root: str):
    """(keys, duplicate_keys, declaration_sites) of SERIES_TABLE by AST
    walk (import-free, works on synthetic trees)."""
    keys: list = []
    dups: list = []
    sites: list = []
    for path, rel in _walk_py(root):
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            try:
                tree = ast.parse(f.read(), path)
            except SyntaxError:
                continue
        for node in ast.walk(tree):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value:
                targets = [node.target]
            if not any(isinstance(t, ast.Name)
                       and t.id == "SERIES_TABLE" for t in targets):
                continue
            sites.append(f"{rel}:{node.lineno}")
            val = node.value
            if isinstance(val, ast.Dict):
                seen = set()
                for k in val.keys:
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        if k.value in seen:
                            dups.append(k.value)
                        seen.add(k.value)
                        keys.append(k.value)
    return keys, dups, sites


def metric_names(root: str) -> set:
    """Every literal registry metric name declared under wormhole_tpu/
    (counter/gauge/histogram call sites — the lint_knobs pattern)."""
    out: set = set()
    for path, _rel in _walk_py(root):
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            out.update(_METRIC_PAT.findall(f.read()))
    return out


def objective_series(root: str) -> dict:
    """series-name -> ["file:line", ...] for every literal series
    handed to an Objective(...) construction."""
    sites: dict = {}
    for path, rel in _walk_py(root):
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            try:
                tree = ast.parse(f.read(), path)
            except SyntaxError:
                continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fname = (node.func.id if isinstance(node.func, ast.Name)
                     else node.func.attr
                     if isinstance(node.func, ast.Attribute) else "")
            if fname != "Objective":
                continue
            series = None
            if len(node.args) >= 2 \
                    and isinstance(node.args[1], ast.Constant) \
                    and isinstance(node.args[1].value, str):
                series = node.args[1].value
            for kw in node.keywords:
                if kw.arg == "series" \
                        and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    series = kw.value.value
            if series is not None:
                sites.setdefault(series, []).append(
                    f"{rel}:{node.lineno}")
    return sites


def derived_suffixes(root: str) -> dict:
    """suffix -> ["file:line", ...] of literal `+ "_suffix"` series
    emissions in the sampler module."""
    sites: dict = {}
    path = os.path.join(root, "wormhole_tpu", "obs", "timeline.py")
    if not os.path.exists(path):
        return sites
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        text = f.read()
    for m in _SUFFIX_PAT.finditer(text):
        ln = text.count("\n", 0, m.start()) + 1
        sites.setdefault(m.group(1), []).append(
            f"wormhole_tpu/obs/timeline.py:{ln}")
    return sites


def record_fields(root: str) -> dict:
    """field -> ["file:line", ...] of keywords the sampler stamps via
    Registry.record(...), plus the ts/mono stamps record itself adds."""
    sites: dict = {}
    path = os.path.join(root, "wormhole_tpu", "obs", "timeline.py")
    if not os.path.exists(path):
        return sites
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        try:
            tree = ast.parse(f.read(), path)
        except SyntaxError:
            return sites
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "record":
            for kw in node.keywords:
                if kw.arg:
                    sites.setdefault(kw.arg, []).append(
                        f"wormhole_tpu/obs/timeline.py:{node.lineno}")
            for stamp in ("ts", "mono"):   # Registry.record stamps
                sites.setdefault(stamp, []).append(
                    f"wormhole_tpu/obs/timeline.py:{node.lineno}")
    return sites


def _resolves(series: str, keys: list, metrics: set) -> bool:
    """A series resolves through an exact table entry, a registry
    metric name, or a declared `*suffix` rule over a registry metric
    (p50/p99/rate series derived by the sampler)."""
    if series in keys or series in metrics:
        return True
    for k in keys:
        if k.startswith("*") and series.endswith(k[1:]):
            stem = series[:-len(k[1:])]
            if stem in metrics or stem in keys:
                return True
    return False


def run(root: str) -> int:
    if not os.path.isdir(os.path.join(root, "wormhole_tpu")):
        print(f"lint_timeline: no wormhole_tpu package under {root!r}",
              file=sys.stderr)
        return 2
    rc = 0
    keys, dups, decl_sites = series_table(root)
    if len(decl_sites) != 1:
        rc = 1
        print(f"lint_timeline: SERIES_TABLE declared at "
              f"{len(decl_sites)} sites (want exactly 1): "
              f"{', '.join(decl_sites) or 'none'}", file=sys.stderr)
    if dups:
        rc = 1
        print("lint_timeline: duplicate SERIES_TABLE keys (the dict "
              "literal silently keeps the last):", file=sys.stderr)
        for k in dups:
            print(f"  {k}", file=sys.stderr)
    metrics = metric_names(root)
    checked = 0
    for label, sites in (("objective series", objective_series(root)),
                         ("record field", record_fields(root))):
        for name, where in sorted(sites.items()):
            checked += 1
            ok = (_resolves(name, keys, metrics) if label !=
                  "record field" else name in keys)
            if not ok:
                rc = 1
                print(f"lint_timeline: {label} {name!r} does not "
                      f"resolve through SERIES_TABLE "
                      f"({', '.join(where)})", file=sys.stderr)
    for suffix, where in sorted(derived_suffixes(root).items()):
        checked += 1
        if "*" + suffix not in keys:
            rc = 1
            print(f"lint_timeline: derived suffix {suffix!r} emitted "
                  f"without a '*{suffix}' SERIES_TABLE entry "
                  f"({', '.join(where)})", file=sys.stderr)
    if rc == 0:
        print(f"lint_timeline: OK ({checked} series sites resolve "
              f"through {len(keys)} table entries)")
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=".",
                    help="repo root containing wormhole_tpu/ "
                         "(default: cwd)")
    args = ap.parse_args(argv)
    return run(args.root)


if __name__ == "__main__":
    sys.exit(main())

"""Quiet-chip watcher: poll the full fwd kernel until the shared chip is
uncontended (the production kernel's quiet time is ~3.3 ms; contended
windows read 8-10 ms), then run the kfloor attribution suite once and
write the results — contended-chip A/Bs flatten per-stage differences
(time-sliced scheduling charges wall-clock in quanta), so the deletion
probes only mean something when this trips.

Usage: python scripts/kquiet.py [quiet_ms=4.5] [poll_sec=240]
"""
from __future__ import annotations

import sys
import time

import jax
import numpy as np

sys.path.insert(0, ".")
sys.path.insert(0, "scripts")

import kfloor  # noqa: E402
from wormhole_tpu.ops import tilemm  # noqa: E402


def main():
    quiet_ms = float(sys.argv[1]) if len(sys.argv) > 1 else 4.5
    poll_sec = float(sys.argv[2]) if len(sys.argv) > 2 else 240.0
    from wormhole_tpu.data.crec import default_cap
    spec = tilemm.make_spec(kfloor.NB, kfloor.ROWS // tilemm.RSUB,
                            default_cap(kfloor.NNZ, kfloor.NB))
    rng = np.random.default_rng(0)
    buckets = rng.integers(0, kfloor.NB, size=kfloor.ROWS * kfloor.NNZ,
                           dtype=np.int64)
    rows = np.repeat(np.arange(kfloor.ROWS, dtype=np.int64), kfloor.NNZ)
    pw_np, _, _ = tilemm.encode_block(buckets, rows, spec)
    w_np = rng.normal(0, 0.1, kfloor.NB).astype(np.float32)
    pw, w = jax.device_put(pw_np), jax.device_put(w_np)
    fwd = tilemm._build_fwd(spec)
    kfloor._force(fwd(pw, w))       # compile
    for _ in range(30):
        o = fwd(pw, w)
    kfloor._force(o)
    while True:
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(10):
                o = fwd(pw, w)
            kfloor._force(o)
            best = min(best, (time.perf_counter() - t0) / 10)
        stamp = time.strftime("%H:%M:%S")
        print(f"[{stamp}] fwd {best*1e3:.2f} ms "
              f"({'QUIET' if best * 1e3 < quiet_ms else 'contended'})",
              flush=True)
        if best * 1e3 < quiet_ms:
            print("chip quiet — running attribution suite", flush=True)
            sys.argv = ["kfloor"]   # kfloor.main reads argv
            kfloor.main()
            return
        time.sleep(poll_sec)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Regression gate over the ``BENCH_r*.json`` trajectory.

Each roadmap run snapshots ``bench.py`` results into ``BENCH_rNN.json``
(wrapper: ``{cmd, n, parsed, rc, tail}`` where ``parsed`` is the
headline ``{metric, value, unit, vs_baseline, extra}``). This gate walks
the trajectory in run order and fails (exit 1) when the newest run
regresses against its predecessor:

- **Throughput**: every numeric ``*ex_per_sec`` / ``*examples_per_sec``
  / ``*rows_per_sec`` key reachable through ``parsed`` (recursively
  through nested dicts, by dotted path) must not drop below
  ``prev * (1 - tol)``. Default ``--tol 0.25``: the real trajectory's
  worst benign run-to-run ratio is 0.834 (criteo_text r02→r03 and
  e2e_cold_stream r03→r04 — CPU-host noise), so 25% passes history
  while catching a halving.
- **Headline**: ``parsed.value`` is compared only when the two runs'
  ``metric`` names match (r01 reports ``ftrl_async_sgd_examples_per_sec``,
  later runs ``end_to_end_examples_per_sec`` — not comparable).
- **Latency** (lower is better): every numeric ``*p50_ms`` / ``*p99_ms``
  key (the serve phase's tail-latency SLO numbers) must not GROW above
  ``prev * (1 + tol)`` at the same dotted path — a p99 regression gates
  just like a throughput drop, with the inequality flipped.
- **Recovery debt** (absolute): the NEWEST run's ``*recovery_debt_s``
  values (rejoin phase: detection → rejoiner admitted) must stay under
  ``--max-recovery-debt`` — a ceiling, not a trend, because past the
  drill's group timeout the handshake is dead by definition.
- **Hierarchy wire** (absolute + trend): the NEWEST run's
  ``hierarchy.*_bytes_wire`` values must be > 0 (the cross-host leg
  ships real encoded bytes — a zero means the sweep measured nothing)
  and its ``hierarchy.*_wire_ratio`` values must clear
  ``--min-wire-ratio``; the same ratio keys also ride the pairwise
  ``--tol`` machinery (higher is better) so a codec that quietly stops
  compressing gates like a throughput drop.
- **Bigmodel paging** (absolute + trend): the NEWEST run's
  ``bigmodel.bytes_h2d`` must be > 0 (the cold tier paged real rows
  through the ring — zero means the phase never left the hot set) and
  ``bigmodel.bigmodel_over_dense`` must clear ``--min-bigmodel-ratio``;
  the same ratio also rides the pairwise ``--tol`` machinery (higher is
  better), so a paging path that quietly starts stalling the consumer
  gates like a throughput drop.
- **Serve fleet** (absolute + trend): the NEWEST run's
  ``serve_fleet.scaling_1to4`` (1->4 replica qps_at_slo ratio) must
  clear ``--min-fleet-scaling``, its snapshot plane must have shipped
  real bytes (``snapshot.bytes_wire`` > 0) with ``cadence_ratio``
  (full-checkpoint disk-poll bytes over delta wire bytes, same
  freshness cadence) above ``--min-snapshot-ratio``, and the 2x
  overload stage must have HELD the SLO (``overload.x2.p99_ms`` <=
  the run's own ``slo_ms``) — shedding exists precisely so that number
  survives overload. Every ``*qps_at_slo`` key also rides the pairwise
  ``--tol`` machinery (higher is better). ``serve_fleet.*`` latency
  keys are deliberately EXCLUDED from the p50/p99 trend gate: the
  absolute SLO ceiling gates them, and single-core sub-second stage
  tails jitter far beyond any useful ``--tol``. Under ``--slo`` the
  newest run's ``overload.x2.burn`` (phase-local serve_p99 tracker)
  must also stay under ``--max-burn`` — the shed controller engages
  inside the SLO band, so a burning budget at 2x overload means it
  failed its one job.
- **SLO timeline** (``--slo``, absolute): the NEWEST run's per-phase
  ``timeline`` blocks (bench.py ``--sample-itv`` sampler;
  ``obs/timeline.summarize``) must keep their first-vs-last-quartile
  ex/s drift under ``--max-drift`` and every declared SLO objective's
  burn rate under ``--max-burn``. A run with no timeline blocks is
  skipped with a note — absent telemetry is a tooling gap, not a
  violation.
- **Ledger fractions**: when both runs carry a ledger block (bench.py
  ``--out`` telemetry, ``{"ledger": {"frac": {...}}}`` anywhere under
  ``parsed``), the ``unattributed`` and ``residual_stall`` fractions may
  not grow by more than ``--tol-frac`` (absolute, default 0.10) at the
  same path — growth there means wall time leaked out of the accounted
  buckets.

The ``MULTICHIP_r*.json`` trajectory (``bench.py --phases multichip``
snapshots: per-mesh-shape ring/sync/anchor ex/s plus scaling
efficiency) is gated with the same machinery, plus two multichip-only
rules:

- **Scaling trend**: every numeric ``*scaling_efficiency`` key shared
  between consecutive usable runs is higher-is-better under ``--tol``,
  exactly like a throughput key.
- **Scaling floor**: the NEWEST usable run's ``*scaling_efficiency``
  values must each clear ``--min-scaling`` (absolute). The default is
  calibrated to the measured CPU fake-mesh trajectory, where all
  "devices" share the host cores so efficiency sits near ``1/n`` — a
  real multi-chip host clears it by an order of magnitude.

Runs that did not produce a result (``parsed`` null or ``rc != 0`` —
e.g. r05's rc=124 timeout, or the early MULTICHIP dryrun snapshots that
carry no ``parsed`` block at all) are skipped with a note: a crashed
run is the roadmap's problem, not a throughput regression, and must not
poison the comparison chain.

Usage::

    python scripts/bench_check.py                 # gate ./BENCH_r*.json
    python scripts/bench_check.py --dir runs/ --tol 0.2
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_RATE_PAT = re.compile(r"(ex_per_sec|examples_per_sec|rows_per_sec)$")
# lower-is-better keys: serve-phase tail latencies. Deliberately NOT
# `*_ms$` — step_ms etc. are derived from the throughput keys already
# gated above, and double-gating one measurement would double the noise
# exposure.
_LAT_PAT = re.compile(r"(p50_ms|p99_ms)$")
_SCALE_PAT = re.compile(r"scaling_efficiency$")
_FUSED_PAT = re.compile(r"fused_over_split$")
_CACHED_PAT = re.compile(r"cached_over_fused$")
_DEBT_PAT = re.compile(r"recovery_debt_s$")
# hierarchy-phase wire keys, gated only under the hierarchy block (the
# comm_filters / async_ps phases carry same-named leaves with different
# semantics — their payloads are synthetic fixtures, not the 2D sweep)
_BYTES_WIRE_PAT = re.compile(r"bytes_wire$")
_WIRE_RATIO_PAT = re.compile(r"wire_ratio$")
# socket_wire-phase throughput keys (socket_delta_mbps, sim_delta_mbps,
# *_snapshot_mbps), gated only under the socket_wire block: higher is
# better, trend-gated pairwise like the ex/s rates so a socket OR sim
# path that quietly slows down trips the --tol gate
_MBPS_PAT = re.compile(r"_mbps$")
# bigmodel-phase keys, gated only under the bigmodel block (bytes_h2d
# also appears in raw feed stats with different semantics)
_BM_BYTES_PAT = re.compile(r"bytes_h2d$")
_BM_RATIO_PAT = re.compile(r"bigmodel_over_dense$")
# serve_fleet-phase keys, gated only under the serve_fleet block.
# qps_at_slo is a MAXIMUM over the swept offered rates whose merged
# fleet p99 held the SLO — higher is better, like a throughput key.
_QPS_SLO_PAT = re.compile(r"qps_at_slo$")
_LEDGER_FRACS = ("unattributed", "residual_stall")
# default --min-scaling: the measured CPU fake-8-device trajectory sits
# at 0.09-0.13 across the swept shapes (all "devices" share the host
# cores, so ~1/n is the honest ceiling); 0.05 passes that with headroom
# while catching a mesh feed that serializes outright (efficiency ->
# 1/n^2 territory)
_MIN_SCALING = 0.05
# absolute floor on the newest BENCH run's *fused_over_split ratio
# (bench.py --phases tile_fused, same-window interleaved): the fused
# one-grid step exists to beat the two calls it replaces, so on the
# TPU backend < 1.0 is a regression by definition. Re-baselined round
# 7 against the CPU host, where the forced fused path runs the Pallas
# interpreter and still measures 1.028 (median of interleaved passes)
# — 0.95 keeps single-core timing noise from flapping a 2.8% margin
# while catching a real fused-path slowdown; gate TPU runs at 1.0.
_MIN_FUSED_RATIO = 0.95
# absolute floor on the newest BENCH run's *cached_over_fused ratio
# (tile_fused phase, narrow-block cache-on vs cache-off A/B in the
# same interleaved windows). On the TPU backend the phase-shared
# one-hot cache exists to beat the per-phase rebuild it replaces, so
# < 1.0 there is a regression — gate TPU runs at 1.0. The CPU default
# is calibrated to the Pallas interpreter, where the staged planes are
# pure extra numpy work (no VMEM refetch to save): the narrow bench
# geometry measures ~0.08, so 0.05 passes the honest CPU number with
# headroom while still catching a cache path that wedges outright.
_MIN_CACHED_RATIO = 0.05
# the tile_fused phase's resolution records, gated as string PREFIXES
# on the newest run: round 8 widened the fused admissibility, so a
# spill view of the bench file and a wide&deep store must both resolve
# fused, and the cached A/B must run at a geometry whose cache the
# resolver's auto budget genuinely admits (a forced-past-budget cache
# would not compile on the TPU backend, so timing one proves nothing).
# Prefixes, not exact strings: the linear store refines its record to
# "fused_update" when the in-place FTRL variant dispatches — any
# fused-family resolution passes, any split fails.
_TILE_RESOLUTION_EXPECT = {
    "resolved_kernel": "fused",
    "spill_resolved_kernel": "fused",
    "wd_resolved_kernel": "fused",
    "cache_record": "onehot_cache=on",
}
# absolute ceiling on the newest BENCH run's *recovery_debt_s (bench.py
# --phases rejoin: heartbeat detection -> rejoiner admitted, dominated
# on CPU by the rejoiner's checkpoint restore + first-window jit
# compiles). 60s passes the CPU-host cost with headroom while catching
# a replay path that wedges into its GroupTimeout (the drill's
# survivors wait 60s before declaring the handshake dead)
_MAX_RECOVERY_DEBT = 60.0
# absolute floor on the newest BENCH run's hierarchy.*_wire_ratio: the
# cross-host delta leg ships quant8+zlib, which measures ~4.2x on the
# swept dense bucket deltas; 2.0 passes that with headroom while
# catching a chain that silently degrades to the raw codec (ratio -> 1)
_MIN_WIRE_RATIO = 2.0
# absolute floor on the newest BENCH run's socket_wire.socket_delta_mbps
# (bench.py --phases socket_wire: 2-process loopback delta allreduce
# through the full quant8+zlib chain over real TCP sockets). The
# single-core CPU host measures ~55 MB/s raw-payload rate; 2.0 passes
# that with a wide margin while catching a wire that degrades to
# per-frame syscall lockstep or loses its encode/send overlap outright.
# A multi-core host with a real NIC should be gated far higher.
_MIN_SOCKET_MBPS = 2.0
# absolute floor on the newest BENCH run's bigmodel.bigmodel_over_dense
# (paged 16x-oversubscribed table vs the dense hot-size anchor, same
# batch geometry). The single-core CPU host measures ~0.58 with zero
# pipeline overlap available — 0.4 passes that with headroom while
# catching a paging path that collapses to synchronous fills. A real
# TPU host overlaps the host-side plan/page work under the device step
# and should be gated at ~0.8 (the ISSUE's within-20% target).
_MIN_BIGMODEL_RATIO = 0.4
# absolute floor on the newest BENCH run's serve_fleet.scaling_1to4
# (aggregate qps_at_slo at 4 replicas over 1 replica, same p99 SLO).
# On the single-core CPU host every replica thread shares one core, so
# adding replicas buys routing/batching overhead without buying
# compute — two clean runs measured 0.57/0.65. 0.4 passes that with
# headroom while catching a router or snapshot plane that serializes
# the fleet outright. A real multi-host fleet gets a core set per
# replica and should be gated at the ISSUE's 1.6x target.
_MIN_FLEET_SCALING = 0.4
# absolute floor on the newest BENCH run's serve_fleet
# snapshot.cadence_ratio (full-checkpoint disk-poll bytes over delta
# wire bytes at the same freshness cadence). Quant8 deltas on the
# benched FTRL store measure ~15x; 3.0 is the ISSUE's floor and
# catches a publisher that degrades to shipping full frames every
# version (ratio -> ~1 after framing overhead).
_MIN_SNAPSHOT_RATIO = 3.0
# --slo defaults: absolute gates over the newest run's per-phase
# `timeline` blocks (bench.py --sample-itv; obs/timeline.summarize).
# Drift is the first-vs-last-quartile ex/s decay WITHIN a phase — a
# 6-second CPU phase jitters hard, so 0.5 catches a halving without
# flagging warm-up noise; burn > 1.0 means an SLO error budget spends
# faster than its window by definition (obs/slo.py).
_MAX_DRIFT = 0.5
_MAX_BURN = 1.0


def load_runs(bench_dir: str,
              prefix: str = "BENCH") -> List[Tuple[str, Optional[dict]]]:
    """[(run_name, parsed-or-None)] in run order; None = skipped run."""
    out: List[Tuple[str, Optional[dict]]] = []
    for path in sorted(glob.glob(
            os.path.join(bench_dir, f"{prefix}_r*.json"))):
        name = os.path.splitext(os.path.basename(path))[0]
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"bench_check: {name}: unreadable ({e}); skipped")
            out.append((name, None))
            continue
        parsed = doc.get("parsed")
        rc = doc.get("rc", 0)
        if not isinstance(parsed, dict) or rc != 0:
            print(f"bench_check: {name}: no result (rc={rc}); skipped")
            out.append((name, None))
            continue
        out.append((name, parsed))
    return out


def _keys_matching(parsed: dict, pat: "re.Pattern") -> Dict[str, float]:
    """dotted-path -> value for every numeric key under ``parsed`` whose
    leaf name matches ``pat``. Paths (not bare leaf names) keep r02's
    ``e2e.ex_per_sec`` distinct from r03's
    ``e2e_steady_cached.ex_per_sec`` — different benchmarks, never
    compared. An ``attempts`` list (chaos phase: one entry per
    supervised relaunch) contributes only its LAST entry, at the stable
    path ``<p>.latest`` — earlier attempts end at an injected fault and
    their count varies run to run, so comparing them would be noise."""
    found: Dict[str, float] = {}

    def walk(node, path: str) -> None:
        if not isinstance(node, dict):
            return
        for k, v in node.items():
            p = f"{path}.{k}" if path else k
            if k == "attempts" and isinstance(v, list):
                if v and isinstance(v[-1], dict):
                    walk(v[-1], f"{p}.latest")
            elif isinstance(v, dict):
                walk(v, p)
            elif isinstance(v, (int, float)) and not isinstance(v, bool) \
                    and pat.search(k):
                found[p] = float(v)
    walk(parsed, "")
    return found


def rate_keys(parsed: dict) -> Dict[str, float]:
    """Throughput keys (higher is better) under ``parsed``."""
    return _keys_matching(parsed, _RATE_PAT)


def latency_keys(parsed: dict) -> Dict[str, float]:
    """Tail-latency keys (LOWER is better) under ``parsed``."""
    return _keys_matching(parsed, _LAT_PAT)


def scaling_keys(parsed: dict) -> Dict[str, float]:
    """Multichip ``*scaling_efficiency`` keys (higher is better)."""
    return _keys_matching(parsed, _SCALE_PAT)


def ledger_fracs(parsed: dict) -> Dict[str, float]:
    """dotted-path -> fraction for the gated ledger fractions found in
    any ``{"ledger": {"frac": {...}}}`` block under ``parsed``."""
    fracs: Dict[str, float] = {}

    def walk(node, path: str) -> None:
        if not isinstance(node, dict):
            return
        for k, v in node.items():
            p = f"{path}.{k}" if path else k
            if k == "ledger" and isinstance(v, dict) \
                    and isinstance(v.get("frac"), dict):
                for name in _LEDGER_FRACS:
                    fv = v["frac"].get(name)
                    if isinstance(fv, (int, float)):
                        fracs[f"{p}.frac.{name}"] = float(fv)
            elif k == "attempts" and isinstance(v, list):
                # latest attempt only — same rule as _keys_matching
                if v and isinstance(v[-1], dict):
                    walk(v[-1], f"{p}.latest")
            elif isinstance(v, dict):
                walk(v, p)
    walk(parsed, "")
    return fracs


def compare(prev_name: str, prev: dict, cur_name: str, cur: dict,
            tol: float, tol_frac: float) -> List[str]:
    """Regression messages for one consecutive pair (empty = clean)."""
    bad: List[str] = []
    if prev.get("metric") == cur.get("metric") \
            and isinstance(prev.get("value"), (int, float)) \
            and isinstance(cur.get("value"), (int, float)):
        pv, cv = float(prev["value"]), float(cur["value"])
        if pv > 0 and cv < pv * (1.0 - tol):
            bad.append(
                f"headline {cur['metric']}: {cv:.1f} < "
                f"{pv:.1f} * {1 - tol:.2f} ({cur_name} vs {prev_name})")
    prates, crates = rate_keys(prev), rate_keys(cur)
    for key in sorted(set(prates) & set(crates)):
        pv, cv = prates[key], crates[key]
        if key == "value" or pv <= 0:
            continue   # headline handled above (metric-name guarded)
        if cv < pv * (1.0 - tol):
            bad.append(
                f"{key}: {cv:.1f} < {pv:.1f} * {1 - tol:.2f} "
                f"({cv / pv:.2f}x, {cur_name} vs {prev_name})")
    plats, clats = latency_keys(prev), latency_keys(cur)
    for key in sorted(set(plats) & set(clats)):
        # serve_fleet latencies are gated by fleet_gate's ABSOLUTE SLO
        # ceiling instead: its sub-second per-level stages put single-
        # digit-ms tails at the mercy of scheduler jitter (measured
        # run-to-run ratios past 2x at the same offered rate), so a
        # pairwise --tol trend would flap on every clean trajectory
        if ".serve_fleet." in f".{key}.":
            continue
        pv, cv = plats[key], clats[key]
        if pv <= 0:
            continue
        if cv > pv * (1.0 + tol):
            bad.append(
                f"{key}: {cv:.1f}ms > {pv:.1f}ms * {1 + tol:.2f} "
                f"({cv / pv:.2f}x, {cur_name} vs {prev_name}) — "
                "serve tail latency regression")
    pscale, cscale = scaling_keys(prev), scaling_keys(cur)
    for key in sorted(set(pscale) & set(cscale)):
        pv, cv = pscale[key], cscale[key]
        if pv <= 0:
            continue
        if cv < pv * (1.0 - tol):
            bad.append(
                f"{key}: {cv:.4f} < {pv:.4f} * {1 - tol:.2f} "
                f"({cv / pv:.2f}x, {cur_name} vs {prev_name}) — "
                "multichip scaling efficiency regression")
    phr, chr_ = (hier_keys(prev, _WIRE_RATIO_PAT),
                 hier_keys(cur, _WIRE_RATIO_PAT))
    for key in sorted(set(phr) & set(chr_)):
        pv, cv = phr[key], chr_[key]
        if pv <= 0:
            continue
        if cv < pv * (1.0 - tol):
            bad.append(
                f"{key}: {cv:.2f} < {pv:.2f} * {1 - tol:.2f} "
                f"({cv / pv:.2f}x, {cur_name} vs {prev_name}) — "
                "hierarchy wire compression regression")
    psk, csk = (socket_keys(prev, _MBPS_PAT),
                socket_keys(cur, _MBPS_PAT))
    for key in sorted(set(psk) & set(csk)):
        pv, cv = psk[key], csk[key]
        if pv <= 0:
            continue
        if cv < pv * (1.0 - tol):
            bad.append(
                f"{key}: {cv:.1f} < {pv:.1f} * {1 - tol:.2f} "
                f"({cv / pv:.2f}x, {cur_name} vs {prev_name}) — "
                "socket/sim wire throughput regression")
    pbm, cbm = (bigmodel_keys(prev, _BM_RATIO_PAT),
                bigmodel_keys(cur, _BM_RATIO_PAT))
    for key in sorted(set(pbm) & set(cbm)):
        pv, cv = pbm[key], cbm[key]
        if pv <= 0:
            continue
        if cv < pv * (1.0 - tol):
            bad.append(
                f"{key}: {cv:.3f} < {pv:.3f} * {1 - tol:.2f} "
                f"({cv / pv:.2f}x, {cur_name} vs {prev_name}) — "
                "bigmodel paged/dense ratio regression")
    pqs, cqs = (fleet_keys(prev, _QPS_SLO_PAT),
                fleet_keys(cur, _QPS_SLO_PAT))
    for key in sorted(set(pqs) & set(cqs)):
        pv, cv = pqs[key], cqs[key]
        if pv <= 0:
            continue
        if cv < pv * (1.0 - tol):
            bad.append(
                f"{key}: {cv:.1f} < {pv:.1f} * {1 - tol:.2f} "
                f"({cv / pv:.2f}x, {cur_name} vs {prev_name}) — "
                "serve fleet qps-at-SLO regression")
    pfracs, cfracs = ledger_fracs(prev), ledger_fracs(cur)
    for key in sorted(set(pfracs) & set(cfracs)):
        if cfracs[key] > pfracs[key] + tol_frac:
            bad.append(
                f"{key}: {cfracs[key]:.3f} > {pfracs[key]:.3f} + "
                f"{tol_frac:.2f} ({cur_name} vs {prev_name}) — wall "
                "time leaking out of accounted buckets")
    return bad


def scaling_floor(name: str, parsed: dict,
                  min_scaling: float) -> List[str]:
    """Absolute floor on the newest multichip run's scaling efficiency:
    trend gating alone would wave through a trajectory that decays
    within tolerance every round."""
    return [
        f"{key}: {v:.4f} < --min-scaling {min_scaling:.4f} ({name}) — "
        "multichip scaling efficiency below the absolute floor"
        for key, v in sorted(scaling_keys(parsed).items())
        if v < min_scaling]


def fused_ratio_keys(parsed: dict) -> Dict[str, float]:
    """``*fused_over_split`` ratio keys (tile_fused phase)."""
    return _keys_matching(parsed, _FUSED_PAT)


def fused_floor(name: str, parsed: dict, min_ratio: float) -> List[str]:
    """Absolute floor on the newest run's fused/split step ratio: the
    fused kernel replacing the split pair must not be slower than it
    (the measurement is same-window interleaved, so the ratio holds
    even on a contended chip)."""
    return [
        f"{key}: {v:.3f} < --min-fused-ratio {min_ratio:.3f} ({name}) "
        "— fused tile step slower than the split oracle it replaces"
        for key, v in sorted(fused_ratio_keys(parsed).items())
        if v < min_ratio]


def cached_ratio_keys(parsed: dict) -> Dict[str, float]:
    """``*cached_over_fused`` ratio keys (tile_fused phase)."""
    return _keys_matching(parsed, _CACHED_PAT)


def cached_floor(name: str, parsed: dict, min_ratio: float) -> List[str]:
    """Absolute floor on the newest run's cached/fused step ratio: the
    one-hot cache replay must not fall below its backend's calibrated
    floor vs the rebuild it skips (same-window interleaved, so the
    ratio holds even on a contended chip)."""
    return [
        f"{key}: {v:.3f} < --min-cached-ratio {min_ratio:.3f} ({name}) "
        "— one-hot cache replay below the floor vs the per-phase "
        "rebuild"
        for key, v in sorted(cached_ratio_keys(parsed).items())
        if v < min_ratio]


def tile_resolution_gate(name: str, parsed: dict) -> List[str]:
    """Absolute gate on the newest run's tile_fused resolution records:
    every :data:`_TILE_RESOLUTION_EXPECT` key found under a
    ``tile_fused`` block must carry its expected string — a spill view
    or wide&deep store resolving split means the round-8 admissibility
    widening regressed, and a cache record other than ``on`` means the
    cached A/B timed an inadmissible (or disabled) cache. Keys absent
    from the run (pre-round-8 snapshots) are skipped — the records are
    gated, not required retroactively."""
    bad: List[str] = []

    def walk(node, path: str) -> None:
        if not isinstance(node, dict):
            return
        for k, v in node.items():
            p = f"{path}.{k}" if path else k
            if isinstance(v, dict):
                walk(v, p)
            elif isinstance(v, str) and k in _TILE_RESOLUTION_EXPECT \
                    and ".tile_fused" in f".{p}":
                want = _TILE_RESOLUTION_EXPECT[k]
                if not v.startswith(want):
                    bad.append(
                        f"{p}: {v!r} != {want!r} ({name}) — tile_fused "
                        "resolution record regressed")
    walk(parsed, "")
    return bad


def debt_keys(parsed: dict) -> Dict[str, float]:
    """``*recovery_debt_s`` keys (rejoin phase)."""
    return _keys_matching(parsed, _DEBT_PAT)


def debt_ceiling(name: str, parsed: dict, max_debt: float) -> List[str]:
    """Absolute ceiling on the newest run's rejoin recovery debt: a
    run-to-run relative gate would ratchet along with a slowly
    regressing replay path, and the quantity has a hard meaning — past
    the drill's group timeout the survivors give the rejoiner up."""
    return [
        f"{key}: {v:.1f}s > --max-recovery-debt {max_debt:.1f}s "
        f"({name}) — rejoin recovery debt above the absolute ceiling"
        for key, v in sorted(debt_keys(parsed).items())
        if v > max_debt]


def hier_keys(parsed: dict, pat: "re.Pattern") -> Dict[str, float]:
    """``_keys_matching`` restricted to paths under a ``hierarchy``
    block — the wire gates apply to the 2D sweep only."""
    return {p: v for p, v in _keys_matching(parsed, pat).items()
            if ".hierarchy." in f".{p}."}


def hier_wire_gate(name: str, parsed: dict,
                   min_ratio: float) -> List[str]:
    """Absolute gates on the newest run's hierarchy wire leg: measured
    bytes on every cross-host config, and a compression-ratio floor —
    both hard meanings, not trends (zero bytes = the sweep measured
    nothing; ratio -> 1 = the filter chain stopped compressing)."""
    bad = [
        f"{key}: {v:.0f} <= 0 ({name}) — hierarchy cross-host leg "
        "moved no measured wire bytes"
        for key, v in sorted(hier_keys(parsed, _BYTES_WIRE_PAT).items())
        if v <= 0]
    bad += [
        f"{key}: {v:.2f} < --min-wire-ratio {min_ratio:.2f} ({name}) "
        "— hierarchy wire compression below the absolute floor"
        for key, v in sorted(hier_keys(parsed, _WIRE_RATIO_PAT).items())
        if v < min_ratio]
    return bad


def socket_keys(parsed: dict, pat: "re.Pattern") -> Dict[str, float]:
    """``_keys_matching`` restricted to paths under a ``socket_wire``
    block — the socket gates apply to the loopback measurement only
    (the hierarchy block carries same-named wire leaves with SimBus
    semantics)."""
    return {p: v for p, v in _keys_matching(parsed, pat).items()
            if ".socket_wire." in f".{p}."}


def socket_wire_gate(name: str, parsed: dict,
                     min_mbps: float) -> List[str]:
    """Absolute gates on the newest run's socket_wire phase, both hard
    meanings rather than trends: zero wire bytes means the loopback
    processes exchanged nothing measurable (the phase's entire reason
    to exist is real cross-process bytes), and a delta-allreduce rate
    under the floor means the TCP path collapsed — lost overlap,
    per-frame syscall lockstep, or a wedged outbox."""
    bad = [
        f"{key}: {v:.0f} <= 0 ({name}) — socket wire moved no "
        "measured wire bytes"
        for key, v in sorted(
            socket_keys(parsed, _BYTES_WIRE_PAT).items())
        if v <= 0]
    blk = (parsed.get("extra") or {}).get("socket_wire")
    if isinstance(blk, dict):
        v = blk.get("socket_delta_mbps")
        if isinstance(v, (int, float)) and v < min_mbps:
            bad.append(
                f"socket_wire.socket_delta_mbps: {v:.2f} < "
                f"--min-socket-mbps {min_mbps:.2f} ({name}) — socket "
                "delta-allreduce throughput below the absolute floor")
        parity = blk.get("parity_tau0")
        if parity is not None and parity is not True:
            bad.append(
                f"socket_wire.parity_tau0: {parity!r} ({name}) — "
                "socket-vs-sim digests diverged at tau=0")
    return bad


def bigmodel_keys(parsed: dict, pat: "re.Pattern") -> Dict[str, float]:
    """``_keys_matching`` restricted to paths under a ``bigmodel``
    block — the paging gates apply to the cold-tier sweep only."""
    return {p: v for p, v in _keys_matching(parsed, pat).items()
            if ".bigmodel." in f".{p}."}


def bigmodel_gate(name: str, parsed: dict,
                  min_ratio: float) -> List[str]:
    """Absolute gates on the newest run's bigmodel phase: real paged
    bytes on the H2D leg (zero = the sweep never overflowed the hot
    set, so it measured nothing) and a floor on the paged/dense rate
    ratio — the cold tier's whole point is growing the bucket space
    without giving the throughput back."""
    bad = [
        f"{key}: {v:.0f} <= 0 ({name}) — bigmodel phase paged no "
        "measured H2D bytes through the ring"
        for key, v in sorted(bigmodel_keys(parsed, _BM_BYTES_PAT).items())
        if v <= 0]
    bad += [
        f"{key}: {v:.3f} < --min-bigmodel-ratio {min_ratio:.3f} "
        f"({name}) — paged/dense throughput below the absolute floor"
        for key, v in sorted(bigmodel_keys(parsed, _BM_RATIO_PAT).items())
        if v < min_ratio]
    return bad


def fleet_keys(parsed: dict, pat: "re.Pattern") -> Dict[str, float]:
    """``_keys_matching`` restricted to paths under a ``serve_fleet``
    block — the fleet gates apply to the replica sweep only."""
    return {p: v for p, v in _keys_matching(parsed, pat).items()
            if ".serve_fleet." in f".{p}."}


def _fleet_block(parsed: dict) -> Optional[dict]:
    """The newest run's ``serve_fleet`` summary block, if any."""
    blk = (parsed.get("extra") or {}).get("serve_fleet")
    return blk if isinstance(blk, dict) else None


def fleet_gate(name: str, parsed: dict, min_fleet_scaling: float,
               min_snapshot_ratio: float) -> List[str]:
    """Absolute gates on the newest run's serve_fleet phase. All hard
    meanings, not trends: replica scaling below the floor means the
    router/snapshot plane eats the added replicas; zero wire bytes
    means the delta plane shipped nothing; a cadence ratio near 1
    means the publisher degraded to full frames; and an overload p99
    above the run's own SLO means the shed controller failed the one
    scenario it exists for. A run whose block is missing a stage
    (budget-truncated) skips that stage's gate — the truncation is
    already visible in the summary."""
    blk = _fleet_block(parsed)
    if blk is None:
        return []
    bad: List[str] = []
    sc = blk.get("scaling_1to4")
    if isinstance(sc, (int, float)) and sc < min_fleet_scaling:
        bad.append(
            f"serve_fleet.scaling_1to4: {sc:.3f} < --min-fleet-scaling "
            f"{min_fleet_scaling:.3f} ({name}) — 1->4 replica "
            "qps-at-SLO scaling below the absolute floor")
    snap = blk.get("snapshot")
    if isinstance(snap, dict):
        bw = snap.get("bytes_wire")
        if isinstance(bw, (int, float)) and bw <= 0:
            bad.append(
                f"serve_fleet.snapshot.bytes_wire: {bw:.0f} <= 0 "
                f"({name}) — snapshot plane shipped no measured bytes")
        cr = snap.get("cadence_ratio")
        if isinstance(cr, (int, float)) and cr < min_snapshot_ratio:
            bad.append(
                f"serve_fleet.snapshot.cadence_ratio: {cr:.2f} < "
                f"--min-snapshot-ratio {min_snapshot_ratio:.2f} "
                f"({name}) — delta shipping not beating full-checkpoint "
                "polling at the same freshness cadence")
    slo_ms = blk.get("slo_ms")
    x2 = (blk.get("overload") or {}).get("x2")
    if isinstance(x2, dict) and isinstance(slo_ms, (int, float)):
        p99 = x2.get("p99_ms")
        if isinstance(p99, (int, float)) and p99 > slo_ms:
            bad.append(
                f"serve_fleet.overload.x2.p99_ms: {p99:.1f}ms > "
                f"slo_ms {slo_ms:.1f}ms ({name}) — served-traffic p99 "
                "broke the SLO at 2x overload despite shedding")
    return bad


def fleet_burn_gate(name: str, parsed: dict,
                    max_burn: float = _MAX_BURN) -> List[str]:
    """(--slo) ceiling on the serve_fleet 2x-overload burn rate: the
    phase arms a serve/p99_ms ceiling objective and samples it through
    an SLOTracker while the shed controller works — a burn above the
    ceiling means the controller held p99 down too late or not at
    all, spending the error budget faster than its window."""
    blk = _fleet_block(parsed)
    x2 = ((blk or {}).get("overload") or {}).get("x2")
    burn = x2.get("burn") if isinstance(x2, dict) else None
    if isinstance(burn, (int, float)) and burn > max_burn:
        return [
            f"serve_fleet.overload.x2.burn: {burn:.2f} > --max-burn "
            f"{max_burn:.2f} ({name}) — shed controller let the p99 "
            "error budget burn at 2x overload"]
    return []


def timeline_blocks(parsed: dict) -> Dict[str, dict]:
    """Dotted path -> per-phase ``timeline`` block (bench.py --out
    telemetry, ``{"timeline": {...}}`` anywhere under ``parsed``)."""
    out: Dict[str, dict] = {}

    def walk(node, path):
        if not isinstance(node, dict):
            return
        for k, v in node.items():
            p = f"{path}.{k}" if path else str(k)
            if k == "timeline" and isinstance(v, dict):
                out[p] = v
            elif isinstance(v, dict):
                walk(v, p)

    walk(parsed, "")
    return out


def slo_gate(name: str, parsed: dict, max_drift: float = _MAX_DRIFT,
             max_burn: float = _MAX_BURN) -> List[str]:
    """Absolute SLO gate on the newest run's timeline blocks: in-phase
    ex/s quartile drift and per-objective burn rates (obs/slo.py). A
    run with no timeline blocks (sampler off, or a pre-timeline
    snapshot) is skipped with a note — absent telemetry is a tooling
    gap, not an SLO violation."""
    blocks = timeline_blocks(parsed)
    if not blocks:
        print(f"bench_check: {name}: no timeline blocks; "
              "--slo gate skipped")
        return []
    bad: List[str] = []
    for path, tl in sorted(blocks.items()):
        exs = tl.get("ex_per_sec")
        drift = exs.get("drift_frac") if isinstance(exs, dict) else None
        if isinstance(drift, (int, float)) and drift > max_drift:
            bad.append(
                f"{path}.ex_per_sec.drift_frac: {drift:.3f} > "
                f"--max-drift {max_drift:.3f} ({name}) — throughput "
                "decaying within the phase")
        for obj, row in sorted((tl.get("slo") or {}).items()):
            burn = row.get("burn") if isinstance(row, dict) else None
            if isinstance(burn, (int, float)) and burn > max_burn:
                bad.append(
                    f"{path}.slo.{obj}.burn: {burn:.2f} > --max-burn "
                    f"{max_burn:.2f} ({name}) — SLO error budget "
                    "spending faster than its window")
    return bad


def _gate_trajectory(prefix: str, bench_dir: str, tol: float,
                     tol_frac: float, all_pairs: bool,
                     min_scaling: float, min_fused_ratio: float,
                     max_recovery_debt: float, slo: bool = False,
                     min_cached_ratio: float = _MIN_CACHED_RATIO,
                     max_drift: float = _MAX_DRIFT,
                     max_burn: float = _MAX_BURN,
                     min_wire_ratio: float = _MIN_WIRE_RATIO,
                     min_bigmodel_ratio: float = _MIN_BIGMODEL_RATIO,
                     min_fleet_scaling: float = _MIN_FLEET_SCALING,
                     min_snapshot_ratio: float = _MIN_SNAPSHOT_RATIO,
                     min_socket_mbps: float = _MIN_SOCKET_MBPS
                     ) -> Tuple[List[str], int, int]:
    """(failures, pairs_compared, keys_compared) for one run prefix."""
    runs = [(n, p) for n, p in load_runs(bench_dir, prefix)
            if p is not None]
    failures: List[str] = []
    if prefix == "MULTICHIP" and runs:
        failures.extend(scaling_floor(*runs[-1], min_scaling))
    if prefix == "BENCH" and runs:
        failures.extend(fused_floor(*runs[-1], min_fused_ratio))
        failures.extend(cached_floor(*runs[-1], min_cached_ratio))
        failures.extend(tile_resolution_gate(*runs[-1]))
        failures.extend(debt_ceiling(*runs[-1], max_recovery_debt))
        failures.extend(hier_wire_gate(*runs[-1], min_wire_ratio))
        failures.extend(bigmodel_gate(*runs[-1], min_bigmodel_ratio))
        failures.extend(fleet_gate(*runs[-1], min_fleet_scaling,
                                   min_snapshot_ratio))
        failures.extend(socket_wire_gate(*runs[-1], min_socket_mbps))
        if slo:
            failures.extend(fleet_burn_gate(*runs[-1],
                                            max_burn=max_burn))
    if slo and runs:
        failures.extend(slo_gate(*runs[-1], max_drift=max_drift,
                                 max_burn=max_burn))
    if len(runs) < 2:
        print(f"bench_check: {len(runs)} usable {prefix} run(s) under "
              f"{bench_dir!r}; nothing to gate pairwise")
        return failures, 0, 0
    pairs = list(zip(runs, runs[1:])) if all_pairs else [runs[-2:]]
    compared = 0
    for (pn, pp), (cn, cp) in pairs:
        compared += len(set(rate_keys(pp)) & set(rate_keys(cp)))
        compared += len(set(latency_keys(pp)) & set(latency_keys(cp)))
        compared += len(set(scaling_keys(pp)) & set(scaling_keys(cp)))
        compared += len(set(fleet_keys(pp, _QPS_SLO_PAT))
                        & set(fleet_keys(cp, _QPS_SLO_PAT)))
        compared += len(set(socket_keys(pp, _MBPS_PAT))
                        & set(socket_keys(cp, _MBPS_PAT)))
        failures.extend(compare(pn, pp, cn, cp, tol, tol_frac))
    return failures, len(pairs), compared


def run(bench_dir: str, tol: float, tol_frac: float,
        all_pairs: bool = False, min_scaling: float = _MIN_SCALING,
        min_fused_ratio: float = _MIN_FUSED_RATIO,
        max_recovery_debt: float = _MAX_RECOVERY_DEBT,
        slo: bool = False,
        min_cached_ratio: float = _MIN_CACHED_RATIO,
        max_drift: float = _MAX_DRIFT,
        max_burn: float = _MAX_BURN,
        min_wire_ratio: float = _MIN_WIRE_RATIO,
        min_bigmodel_ratio: float = _MIN_BIGMODEL_RATIO,
        min_fleet_scaling: float = _MIN_FLEET_SCALING,
        min_snapshot_ratio: float = _MIN_SNAPSHOT_RATIO,
        min_socket_mbps: float = _MIN_SOCKET_MBPS) -> int:
    failures: List[str] = []
    pairs = compared = 0
    for prefix in ("BENCH", "MULTICHIP"):
        f, p, c = _gate_trajectory(prefix, bench_dir, tol, tol_frac,
                                   all_pairs, min_scaling,
                                   min_fused_ratio, max_recovery_debt,
                                   slo=slo,
                                   min_cached_ratio=min_cached_ratio,
                                   max_drift=max_drift,
                                   max_burn=max_burn,
                                   min_wire_ratio=min_wire_ratio,
                                   min_bigmodel_ratio=min_bigmodel_ratio,
                                   min_fleet_scaling=min_fleet_scaling,
                                   min_snapshot_ratio=min_snapshot_ratio,
                                   min_socket_mbps=min_socket_mbps)
        failures.extend(f)
        pairs += p
        compared += c
    if failures:
        print(f"bench_check: {len(failures)} regression(s):",
              file=sys.stderr)
        for msg in failures:
            print(f"  {msg}", file=sys.stderr)
        return 1
    print(f"bench_check: OK ({pairs} pair(s), {compared} shared "
          f"throughput/latency/scaling keys, tol {tol:.0%}, ledger tol "
          f"+{tol_frac:.2f}, scaling floor {min_scaling})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=".",
                    help="directory holding BENCH_r*.json (default: cwd)")
    ap.add_argument("--tol", type=float, default=0.25,
                    help="relative throughput drop tolerated vs the "
                         "previous run (default 0.25; history's worst "
                         "benign ratio is 0.834)")
    ap.add_argument("--tol-frac", type=float, default=0.10,
                    help="absolute growth tolerated in the ledger "
                         "unattributed/residual_stall fractions "
                         "(default 0.10)")
    ap.add_argument("--min-scaling", type=float, default=_MIN_SCALING,
                    help="absolute floor on the newest MULTICHIP run's "
                         "*scaling_efficiency values (default "
                         f"{_MIN_SCALING}; the CPU fake-mesh trajectory "
                         "measures ~1/n_devices)")
    ap.add_argument("--min-fused-ratio", type=float,
                    default=_MIN_FUSED_RATIO,
                    help="absolute floor on the newest BENCH run's "
                         "*fused_over_split ratio (default "
                         f"{_MIN_FUSED_RATIO}, CPU-calibrated: the "
                         "interpret-mode fused step measures 1.028 vs "
                         "split; gate TPU runs at 1.0 — the fused step "
                         "must not be slower than the split oracle)")
    ap.add_argument("--min-cached-ratio", type=float,
                    default=_MIN_CACHED_RATIO,
                    help="absolute floor on the newest BENCH run's "
                         "*cached_over_fused ratio (default "
                         f"{_MIN_CACHED_RATIO}, CPU-calibrated: the "
                         "interpret-mode cache replay measures ~0.08 "
                         "because the staged planes are pure extra "
                         "work there; gate TPU runs at 1.0 — the "
                         "cache must beat the rebuild it skips)")
    ap.add_argument("--max-recovery-debt", type=float,
                    default=_MAX_RECOVERY_DEBT,
                    help="absolute ceiling (seconds) on the newest "
                         "BENCH run's *recovery_debt_s (default "
                         f"{_MAX_RECOVERY_DEBT}; rejoin phase, "
                         "detection -> admission)")
    ap.add_argument("--min-wire-ratio", type=float,
                    default=_MIN_WIRE_RATIO,
                    help="absolute floor on the newest BENCH run's "
                         "hierarchy.*_wire_ratio values (default "
                         f"{_MIN_WIRE_RATIO}; quant8+zlib measures "
                         "~4.2x on the swept dense bucket deltas)")
    ap.add_argument("--min-bigmodel-ratio", type=float,
                    default=_MIN_BIGMODEL_RATIO,
                    help="absolute floor on the newest BENCH run's "
                         "bigmodel.bigmodel_over_dense (default "
                         f"{_MIN_BIGMODEL_RATIO}, calibrated to the "
                         "single-core CPU host; gate a real TPU host "
                         "at ~0.8)")
    ap.add_argument("--min-fleet-scaling", type=float,
                    default=_MIN_FLEET_SCALING,
                    help="absolute floor on the newest BENCH run's "
                         "serve_fleet.scaling_1to4 (default "
                         f"{_MIN_FLEET_SCALING}, calibrated to the "
                         "single-core CPU host where replicas share "
                         "one core; gate a real multi-host fleet at "
                         "the 1.6x target)")
    ap.add_argument("--min-snapshot-ratio", type=float,
                    default=_MIN_SNAPSHOT_RATIO,
                    help="absolute floor on the newest BENCH run's "
                         "serve_fleet snapshot.cadence_ratio (default "
                         f"{_MIN_SNAPSHOT_RATIO}; quant8 deltas on the "
                         "benched FTRL store measure ~15x)")
    ap.add_argument("--min-socket-mbps", type=float,
                    default=_MIN_SOCKET_MBPS,
                    help="absolute floor on the newest BENCH run's "
                         "socket_wire.socket_delta_mbps (default "
                         f"{_MIN_SOCKET_MBPS}, CPU-calibrated: the "
                         "single-core loopback host measures ~55 MB/s "
                         "raw-payload rate; gate a real NIC far higher)")
    ap.add_argument("--all-pairs", action="store_true",
                    help="gate every consecutive pair in the "
                         "trajectory, not just the newest one")
    ap.add_argument("--slo", action="store_true",
                    help="also gate the newest run's per-phase "
                         "`timeline` blocks: ex/s drift and SLO burn "
                         "rates (skipped with a note when the run "
                         "carries no timeline)")
    ap.add_argument("--max-drift", type=float, default=_MAX_DRIFT,
                    help="(--slo) ceiling on a phase's first-vs-last-"
                         "quartile ex/s decay fraction (default "
                         f"{_MAX_DRIFT})")
    ap.add_argument("--max-burn", type=float, default=_MAX_BURN,
                    help="(--slo) ceiling on any SLO objective's burn "
                         f"rate (default {_MAX_BURN}; > 1.0 spends the "
                         "error budget faster than its window)")
    args = ap.parse_args(argv)
    return run(args.dir, args.tol, args.tol_frac,
               all_pairs=args.all_pairs, min_scaling=args.min_scaling,
               min_fused_ratio=args.min_fused_ratio,
               max_recovery_debt=args.max_recovery_debt,
               slo=args.slo, min_cached_ratio=args.min_cached_ratio,
               max_drift=args.max_drift,
               max_burn=args.max_burn,
               min_wire_ratio=args.min_wire_ratio,
               min_bigmodel_ratio=args.min_bigmodel_ratio,
               min_fleet_scaling=args.min_fleet_scaling,
               min_snapshot_ratio=args.min_snapshot_ratio,
               min_socket_mbps=args.min_socket_mbps)


if __name__ == "__main__":
    sys.exit(main())

"""Interleaved kernel A/B sweep — contention-robust variant comparison.

The shared chip's bursty contention makes sequential A/B meaningless
(round-4 finding), so variants are timed in ALTERNATING short windows:
every variant samples the same contention profile and the per-variant
MINIMUM approximates its uncontended time. Sweeps cap (pad waste vs
exact-overflow scatter cost) and tiles_step.

Usage: python scripts/ksweep.py [caps] [tbs]   e.g. 1280,1408,1536 8,16
"""
from __future__ import annotations

import dataclasses
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from wormhole_tpu.ops import tilemm  # noqa: E402

NB = 1 << 22
ROWS = 98304
NNZ = 39


def _force(o):
    float(np.asarray(jax.tree_util.tree_leaves(o)[0].ravel()[0]))


def build_variant(cap: int, tb: int, rng):
    # fuse=1: swept tiles_step values need not divide an auto-picked fuse
    spec = dataclasses.replace(
        tilemm.make_spec(NB, ROWS // tilemm.RSUB, cap), tiles_step=tb,
        fuse=1)
    buckets = rng.integers(0, NB, size=ROWS * NNZ, dtype=np.int64)
    rows = np.repeat(np.arange(ROWS, dtype=np.int64), NNZ)
    pw_np, ovb, ovr = tilemm.encode_block(buckets, rows, spec)
    oc = max(128, -(-len(ovb) // 128) * 128) if len(ovb) else 0
    print(f"cap={cap} tb={tb}: overflow {len(ovb)} pairs (oc={oc})")
    pw = jax.device_put(pw_np)
    if oc:
        ovb_p = np.full(oc, 0xFFFFFFFF, np.uint32)
        ovr_p = np.zeros(oc, np.uint32)
        ovb_p[:len(ovb)] = ovb
        ovr_p[:len(ovr)] = ovr
        ovb_d, ovr_d = jax.device_put(ovb_p), jax.device_put(ovr_p)
    else:
        ovb_d = ovr_d = None
    w = jax.device_put(rng.normal(0, 0.1, NB).astype(np.float32))
    dual = jax.device_put(rng.normal(0, 1.0, ROWS).astype(np.float32))

    @jax.jit
    def step(w, dual):
        mg = tilemm.forward_margins(pw, w, spec, ovb_d, ovr_d)
        g = tilemm.backward_grad(pw, dual, spec, ovb_d, ovr_d)
        return mg, g

    return step, (w, dual)


def main():
    caps = [int(c) for c in (sys.argv[1].split(",") if len(sys.argv) > 1
                             else ["1408"])]
    tbs = [int(t) for t in (sys.argv[2].split(",") if len(sys.argv) > 2
                            else ["16"])]
    rng = np.random.default_rng(0)
    variants = {}
    for cap in caps:
        for tb in tbs:
            variants[(cap, tb)] = build_variant(cap, tb, rng)
    # compile + burn everything first
    for step, args in variants.values():
        for _ in range(40):
            o = step(*args)
        _force(o)
    best = {k: float("inf") for k in variants}
    REPS, WINDOWS = 5, 12
    for _ in range(WINDOWS):
        for k, (step, args) in variants.items():   # interleave
            t0 = time.perf_counter()
            o = None
            for _ in range(REPS):
                o = step(*args)
            _force(o)
            best[k] = min(best[k], (time.perf_counter() - t0) / REPS)
    for (cap, tb), t in sorted(best.items()):
        print(f"cap={cap} tb={tb}: {t*1e3:7.3f} ms/step "
              f"-> {ROWS/t/1e6:.2f} M ex/s (fwd+bwd)")


if __name__ == "__main__":
    main()
